"""Benchmark: flagship north-star row — GPT-2 350M causal-LM training on
one chip (the best measured MFU config from the benchmarks/model_bench.py
sweeps; VERDICT r2 next-#1).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

``python bench.py serving`` instead runs the Poisson-arrival serving row:
continuous batching (deepspeed_tpu/serving/) vs the batch-synchronous
"gang" discipline ``generate()`` imposes, SAME engine/kernels/slot count,
only the admission policy differs. Reports req/s and p50/p99 TTFT for
both arms; ``vs_baseline`` = continuous req/s over gang req/s.

``python bench.py spec`` runs the speculative-decoding row: n-gram
(prompt-lookup) draft + one fixed-shape ``verify_k`` forward vs plain
one-token decode, same engine/slots/workload, on a repetitive-text
workload. Reports tokens per slot-decode-step (plain pins this at
exactly 1.0), draft acceptance rate and draft overhead, and checks the
greedy outputs are bitwise identical between arms; ``vs_baseline`` =
spec tokens/s over plain tokens/s (wall-clock).

``python bench.py serving-stall`` runs the stall-free admission row:
chunked prefill interleaved with decode plus batched bucketed admission
(``prefill_chunk > 0``) vs the PR-2 serial whole-prompt admission
(``prefill_chunk=0``), SAME engine/kernels/slots/policy, only the
admission path differs. The workload mixes short prompts with long ones
whose serial prefill stalls every live decode slot (and, landing between
power-of-two width buckets, pads to the next bucket in serial but only
to the next chunk when chunked); reports TTFT p50/p99, per-token p99,
p50/p99 inter-token step gap and req/s for both arms (median of 3
interleaved replays), checks greedy outputs are bitwise identical across
arms and replays and that the decode program did not recompile after
warmup; ``vs_baseline`` = serial inter-token-gap p99 over stall-free
inter-token-gap p99 (>1 means the streaming tail shrank).

``python bench.py paging`` runs the paged-KV row: a PagedKVPool server
(refcounted pages + radix-trie prefix cache + copy-on-write) vs the
contiguous SlotPool at the SAME KV HBM budget, on a >=50%-shared-prefix
workload. The paged arm runs 2x the slots in the same bytes (shared
pages are mapped, not copied); reports peak resident requests at equal
HBM (headline, gate >= 1.5), served requests per KV-GB, TTFT cold vs
prefix-hit, prefix hit rate, CoW forks, peak pages in use, and the
zero-recompile gate after a warm all-hits replay; greedy outputs must
be bitwise identical across arms.

``python bench.py serving-decode`` runs the raw-decode-speed row: the
fused Pallas paged-attention decode kernel plus overlapped host
scheduling (``paged_kv={"kernel": "on"}, overlap=True``) vs the dense
gather/scatter oracle with serial stepping, SAME engine/pool geometry
on a decode-heavy workload; greedy outputs must be bitwise identical
across arms and replications. Reports p50/p99 inter-token step gap
(headline: the kernel arm's p99; ``vs_baseline`` = dense p99 over it),
per-token latency, tokens/s ratio, and MFU from the runtime cost model
(``check_regression.py --warn-metric detail.efficiency.mfu``); carries
the zero-recompile gate (``--max-recompiles 0``) and the
``--signatures`` manifest for ``--require-signature-match``.

``python bench.py serving-tp`` runs the multi-chip serving row on the
forced 8-device CPU host (``--xla_force_host_platform_device_count=8``,
exported before the row's own jax import): TP=1 (mesh ``data=8``) vs
TP=2 (``data=4, model=2``) with bitwise-identical greedy outputs across
mesh shapes (only shardings move; jit signatures do not), plus a DP=2
``ReplicaRouter`` over two paged replicas on disjoint 4-device meshes
vs one identically-configured replica, on a 4-session-group workload
whose prefixes overflow a single page pool. Headline ``vs_baseline`` =
router req/s over single-replica req/s (session affinity keeps each
group's prefix resident where the single pool thrashes), gated by
``check_regression.py --threshold 1.5`` together with
``--max-recompiles 0 --require-zero-leaks --require-signature-match``.

``python bench.py serving-async`` runs the async front-end row: the
stdlib asyncio HTTP/SSE server (deepspeed_tpu/serving/frontend/) on a
localhost socket with Poisson arrivals at three priority tiers
(interactive / standard / batch) from a hand-rolled asyncio client.
The standard tier's TTFT contract is unmeetable by construction, so
its SLO burn pages and the priority scheduler sheds the batch tier
(HTTP 429 + Retry-After) while interactive traffic keeps flowing.
Headline ``value`` (and ``detail.efficiency.goodput_slo``, gated by
``check_regression.py --min-goodput``) is the TOP-class (interactive)
goodput measured while the bottom class is actively shed; the row also
gates on zero slot leaks, clean ``check_invariants``, complete request
timelines and zero post-warmup recompiles across the whole
HTTP -> bridge -> step-thread path (``--require-zero-leaks`` +
``--max-recompiles 0``).

``--json <path>`` additionally writes the full result object to
``<path>`` (e.g. ``BENCH_serving.json``) for dashboards/drivers.
``check_regression.py`` diffs two such files and gates on named
metrics (and on ``detail.recompiles_after_warmup`` via
``--max-recompiles`` — every serving row reports it from the runtime
recompile watchdog after a post-run warm replay).  The static side of
the same gate is ``--lint-json`` (repeatable): an all-tiers
``bin/graftlint --json`` report plus a ``bin/graftlint --tier own
deepspeed_tpu/serving --json`` ownership report, both held at
``--max-lint-errors 0`` — the lifecycle invariants the chaos row
audits at runtime are proven on every exception path before the row
runs.

``--trace <path>`` additionally writes a Chrome trace-event / Perfetto
JSON timeline (open at ui.perfetto.dev) for the row: serving rows run
one extra traced replay on the warmed server (step-phase spans +
per-request lifecycle lanes + flow events) and report the tracer's
throughput overhead vs an untraced replay; the training row traces one
extra ``train_batch`` step.

``--dump-dir <path>`` (serving-chaos): the row ends with a
flight-recorder drill — a planted ``state_corruption`` fault followed
by the ``check_invariants`` audit must drop EXACTLY ONE post-mortem
JSON under ``<path>`` (a tmpdir when the flag is absent). The
serving-stall and paging rows also report an ``efficiency`` detail
block (MFU, goodput vs generous SLO targets, KV-HBM drift against the
page math, telemetry ``overhead_pct``) from the runtime cost model +
SLO tracker; ``check_regression.py --min-goodput/--max-overhead-pct``
gate on it.

``--signatures <path>`` (serving-stall, paging, serving-decode): each
arm exports (and
merge-unions into) a ``signatures.json`` warmup manifest — the exact
abstract signature each watched jitted program was traced with during
warmup — for ``bin/graftlint --check --manifest`` and the
``check_regression.py --require-signature-match`` gate: the statically
enumerated reachable-signature set must equal the runtime warmup set
in both directions.

``vs_baseline`` compares achieved model TFLOPS against the reference's
headline single-device number: 64 TFLOPS/GPU for BERT-Large pretraining
with DeepSpeed's fused kernels on V100-32GB (BASELINE.md row 1, reference
docs/_tutorials/bert-pretraining.md:392). The reference's accounting
counts the FULL attention matmuls (the Megatron 96·B·S·L·h²(1+S/6h+...)
convention behind that 64-TFLOPS claim), so ``vs_baseline`` uses the same;
``detail`` also reports the stricter 6N-only and causal-halved-attention
numbers, and MFU against the v5e bf16 peak (197 TFLOPS) under each.
"""

from __future__ import annotations

import json
import time

import numpy as np

V5E_PEAK_TFLOPS = 197.0

_JSON_PATH = None   # set by __main__ from --json <path>
_TRACE_PATH = None  # set by __main__ from --trace <path>
_DUMP_DIR = None    # set by __main__ from --dump-dir <path>; chaos-row
#                     post-mortem JSONs land here (tmpdir if unset)
_SIGNATURES_PATH = None  # set by __main__ from --signatures <path>;
#                     serving rows export the runtime warmup manifest
#                     (signatures.json) for graftlint --check / the
#                     check_regression.py --require-signature-match gate


def _emit(result: dict) -> None:
    """Print the one-line JSON row; mirror it to --json <path> if given."""
    print(json.dumps(result))
    if _JSON_PATH:
        with open(_JSON_PATH, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")


def _enable_persistent_cache():
    """Persistent XLA compilation cache: once this bench's programs have
    compiled on this machine, later runs (the driver's end-of-round run)
    reuse them even while the tunneled remote-compile service is down."""
    import os

    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)


def main():
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    SEQ = 1024
    # measured frontier (benchmarks/model_bench_results.json): 350M at
    # mbs 10 x gas 16 with selective ("dots") remat is the best MFU row
    # this chip fits; mbs 16 OOMs at 350M, mbs 8/12 measure slower
    MICRO_BS = 10
    GAS = 16
    N_EMBD, N_LAYER, N_HEAD = 1024, 24, 16

    cfg = GPT2Config(vocab_size=50257, n_positions=SEQ, n_embd=N_EMBD,
                     n_layer=N_LAYER, n_head=N_HEAD, dtype=jnp.bfloat16,
                     remat=True, remat_policy="dots")
    model = GPT2LMHeadModel(cfg)
    config = {
        "train_micro_batch_size_per_gpu": MICRO_BS,
        "gradient_accumulation_steps": GAS,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam",
                      "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size,
            (engine.train_batch_size(), SEQ)).astype(np.int32)}

    # warmup (compile)
    for _ in range(2):
        loss = engine.train_batch(batch=make_batch())
    jax.block_until_ready(loss)

    steps = 5
    batches = [make_batch() for _ in range(steps)]
    t0 = time.perf_counter()
    for b in batches:
        loss = engine.train_batch(batch=b)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens_per_step = engine.train_batch_size() * SEQ
    tok_s_chip = tokens_per_step * steps / dt / n_chips

    trace_events = None
    if _TRACE_PATH:
        # one extra traced step AFTER timing (train_batch phase spans)
        from deepspeed_tpu.telemetry import Tracer

        engine.tracer = Tracer()
        jax.block_until_ready(engine.train_batch(batch=make_batch()))
        trace_events = engine.tracer.export(_TRACE_PATH)

    n_params = engine.num_parameters
    # three accountings, strictest to reference-convention (see module doc)
    attn_full = 12 * N_LAYER * SEQ * N_EMBD       # QK^T + AV, fwd+bwd
    f_6n = 6 * n_params
    f_causal = f_6n + attn_full // 2              # only the causal half is
    f_full = f_6n + attn_full                     # real work; full = ref conv.
    tf = {k: tok_s_chip * f / 1e12
          for k, f in (("6n", f_6n), ("causal_attn", f_causal),
                       ("full_attn", f_full))}

    _emit({
        "metric": "GPT-2 350M seq1024 bf16 ZeRO-2 training throughput "
                  "(mbs10 x gas16, dots remat)",
        "value": round(tok_s_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tf["full_attn"] / 64.0, 3),
        "detail": {
            "baseline": "DeepSpeed BERT-Large 64 TFLOPS on 1xV100-32GB "
                        "(full-attention accounting, as the reference uses)",
            "n_chips": n_chips,
            "params_m": round(n_params / 1e6, 1),
            "tflops_6n": round(tf["6n"], 2),
            "tflops_causal_attn": round(tf["causal_attn"], 2),
            "tflops_full_attn": round(tf["full_attn"], 2),
            "mfu_pct_6n": round(100 * tf["6n"] / V5E_PEAK_TFLOPS, 1),
            "mfu_pct_causal_attn": round(
                100 * tf["causal_attn"] / V5E_PEAK_TFLOPS, 1),
            "mfu_pct_full_attn": round(
                100 * tf["full_attn"] / V5E_PEAK_TFLOPS, 1),
            "loss": float(loss),
            "tracer": ({"path": _TRACE_PATH, "events": trace_events}
                       if _TRACE_PATH else None),
        },
    })


def serving_main():
    """Poisson-arrival serving row: continuous vs gang scheduling."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        n_req, slots, rate = 32, 4, 200.0
        len_lo, len_hi, gen_lo, gen_hi = 8, 48, 4, 48
    else:
        # GPT-2 124M-ish decode under a bursty open-loop arrival process
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots, rate = 64, 8, 48.0
        len_lo, len_hi, gen_lo, gen_hi = 32, 256, 16, 128

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    # one workload, replayed identically into both arms: bursty Poisson
    # arrivals, mixed prompt lengths, mixed generation budgets (length
    # spread is exactly what gang scheduling wastes slots on)
    arrivals = np.cumsum(gen.exponential(1.0 / rate, size=n_req))
    prompts = [gen.integers(0, cfg.vocab_size,
                            size=int(gen.integers(len_lo, len_hi + 1))
                            ).astype(np.int32) for _ in range(n_req)]
    budgets = gen.integers(gen_lo, gen_hi + 1, size=n_req)

    def run_arm(policy: str, tracer=None):
        srv = ServingEngine(engine, num_slots=slots, max_queue_depth=n_req,
                            policy=policy, tracer=tracer)
        t0 = time.perf_counter()
        i = 0
        while i < n_req or srv.pending or srv.live_count:
            now = time.perf_counter() - t0
            while i < n_req and arrivals[i] <= now:
                srv.submit(prompts[i], max_new_tokens=int(budgets[i]))
                i += 1
            if not (srv.pending or srv.live_count):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
                continue
            srv.step()
        return srv.stats(), srv

    # warmup: compile every prefill bucket + admit + decode + sample once;
    # must include len_hi so the TOP bucket is compiled before timing starts
    warm = ServingEngine(engine, num_slots=slots, max_queue_depth=n_req)
    w = len_lo
    while True:
        warm.submit(np.zeros((w,), np.int32), max_new_tokens=2)
        if w >= len_hi:
            break
        w = min(w * 2, len_hi)
    warm.run_until_drained()
    # ...and every BATCHED admission combo: stall-free admission compiles
    # one program per (rows, bucket) pair, so same-bucket pairs and
    # slot-full groups must run here or the timed Poisson run (and the
    # post-run recompile probe) pays first-touch compiles mid-flight
    w = len_lo
    while True:
        for group in (2, slots):
            for _ in range(group):
                warm.submit(np.zeros((w,), np.int32), max_new_tokens=2)
            warm.run_until_drained()
        if w >= len_hi:
            break
        w = min(w * 2, len_hi)
    # ...and every BATCHED admission combo: stall-free admission compiles
    # one program per (rows, bucket) pair, so same-bucket pairs and
    # slot-full groups must run here or the timed Poisson run (and the
    # post-run recompile probe) pays first-touch compiles mid-flight
    w = len_lo
    while True:
        for group in (2, slots):
            for _ in range(group):
                warm.submit(np.zeros((w,), np.int32), max_new_tokens=2)
            warm.run_until_drained()
        if w >= len_hi:
            break
        w = min(w * 2, len_hi)

    cont, srv_cont = run_arm("continuous")
    gang, _ = run_arm("gang")

    # recompile probe — AFTER timing: declare warmup over on the fully
    # exercised server and replay a slice of the workload; any cache
    # growth now is real compilation churn (the gate --max-recompiles
    # reads this as detail.recompiles_after_warmup)
    srv_cont.end_warmup()
    for p, b in zip(prompts[:8], budgets[:8]):
        srv_cont.submit(p, max_new_tokens=int(b))
    srv_cont.run_until_drained()
    recompiles = srv_cont.watchdog.recompiles

    tracer_detail = None
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        # overhead = traced vs untraced replay of the SAME warmed arm
        base, _ = run_arm("continuous")
        traced, srv_tr = run_arm("continuous", tracer=Tracer())
        n_events = srv_tr.tracer.export(_TRACE_PATH)
        overhead = 100.0 * (base["requests_per_s"] -
                            traced["requests_per_s"]) / base["requests_per_s"]
        tracer_detail = {
            "path": _TRACE_PATH, "events": n_events,
            "traced_requests_per_s": round(traced["requests_per_s"], 3),
            "untraced_requests_per_s": round(base["requests_per_s"], 3),
            "overhead_pct": round(overhead, 2),
        }

    def arm_detail(s):
        return {"requests_per_s": round(s["requests_per_s"], 3),
                "tokens_per_s": round(s["tokens_per_s"], 1),
                "ttft_p50_ms": round(s["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(s["ttft_p99_ms"], 1),
                "per_token_p50_ms": round(s["per_token_p50_ms"], 2),
                "tokens_per_decode_step": round(s["tokens_per_decode_step"],
                                                3),
                "completed": s["completed"]}

    _emit({
        "metric": f"continuous-batching serving, Poisson arrivals "
                  f"({n_req} req @ {rate}/s, {slots} slots, prompts "
                  f"{len_lo}-{len_hi}, budgets {gen_lo}-{gen_hi})",
        "value": round(cont["requests_per_s"], 3),
        "unit": "req/s",
        "vs_baseline": round(cont["requests_per_s"] / gang["requests_per_s"],
                             3),
        "detail": {
            "baseline": "gang (batch-synchronous) admission at equal slot "
                        "count — the generate() discipline on the same "
                        "engine and kernels",
            "recompiles_after_warmup": int(recompiles),
            "tracer": tracer_detail,
            "continuous": arm_detail(cont),
            "gang": arm_detail(gang),
        },
    })


def serving_stall_main():
    """Stall-free admission row: chunked+batched vs serial admission."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.serving.metrics import ServingMetrics

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # runnable locally, but heavy enough that a monolithic
        # long-prompt prefill genuinely stalls concurrent decodes (the
        # phenomenon this row measures needs prefill >> decode cost)
        cfg = TransformerConfig(vocab_size=512, max_seq_len=1024, n_embd=128,
                                n_layer=4, n_head=4, dtype=jnp.float32)
        n_req, slots, rate, chunk = 64, 8, 120.0, 256
        len_lo, len_hi, long_lo, long_hi = 17, 32, 520, 760
        long_every, gen_lo, gen_hi = 8, 24, 32
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots, rate, chunk = 64, 8, 48.0, 256
        len_lo, len_hi, long_lo, long_hi = 32, 128, 520, 760
        long_every, gen_lo, gen_hi = 8, 16, 96

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    # one workload replayed identically into both arms: saturating
    # Poisson arrivals, mostly short prompts (which batched admission
    # coalesces into one dispatch where serial admission pays one
    # full-width dispatch per request), plus a long prompt every
    # ``long_every``-th request — the arrival whose serial prefill
    # stalls every live slot for a whole monolithic dispatch. Under
    # saturation TTFT is queue-drain-bound, so the arm that admits
    # faster finishes faster and wins TTFT across the board.
    arrivals = np.cumsum(gen.exponential(1.0 / rate, size=n_req))
    prompts, budgets = [], []
    for i in range(n_req):
        if i % long_every == long_every - 1:
            T = int(gen.integers(long_lo, long_hi + 1))
        else:
            T = int(gen.integers(len_lo, len_hi + 1))
        prompts.append(gen.integers(0, cfg.vocab_size, size=T)
                       .astype(np.int32))
        budgets.append(int(gen.integers(gen_lo, gen_hi + 1)))

    def warm_arm(srv: ServingEngine) -> None:
        """Compile every program admission can EVER reach BEFORE timing —
        the full statically-enumerable set (graftlint --check proves it
        finite and equal to this sweep), not just the shapes this
        workload's length distribution happens to hit: each singleton
        width bucket up to the arm's clamp (one chunk when stall-free;
        the capacity bucket when serial admission pads whole prompts),
        each (batch-bucket x width-bucket) grouping the token budget
        allows (driven through real closed-loop admissions, so the
        pool's jitted multi-row admit warms too), the chunk program,
        decode and sampling. Warm-by-replay is NOT enough — admission
        grouping depends on wall-clock arrival interleaving, so a
        grouping first seen mid-timed-run would compile inside a timed
        step and masquerade as a stall."""
        sf = srv._stall_free
        w, top = 16, (chunk if sf else 1024)
        while w <= top:
            srv.submit(np.ones((min(w, long_hi),), np.int32),
                       max_new_tokens=2)
            srv.run_until_drained()
            w *= 2
        if sf:
            budget = 2 * chunk + 64 * slots  # == arm_sf construction
            w = 16
            while w <= chunk:
                for count in range(2, min(slots, max(1, budget // w)) + 1):
                    for _ in range(count):
                        srv.submit(np.ones((w,), np.int32),
                                   max_new_tokens=2)
                    srv.run_until_drained()
                w *= 2
        srv.submit(np.ones((long_hi,), np.int32), max_new_tokens=2)
        srv.run_until_drained()

    def run_arm(srv: ServingEngine, timed: bool) -> dict:
        if timed:  # fresh aggregates; warmup polluted them
            srv.metrics = ServingMetrics(None, registry=srv.registry,
                                         step_fn=lambda s=srv: s.step_id)
            srv.reset_efficiency_window()
        reqs = []
        t0 = time.perf_counter()
        i = 0
        while i < n_req or srv.pending or srv.live_count:
            now = time.perf_counter() - t0
            while i < n_req and arrivals[i] <= now:
                reqs.append(srv.submit(prompts[i],
                                       max_new_tokens=budgets[i]))
                i += 1
            if not (srv.pending or srv.live_count):
                time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
                continue
            srv.step()
        s = srv.stats()
        s["outputs"] = [list(r.output_tokens) for r in reqs]
        return s

    # one engine per arm, reused warm->timed, so the timed pass replays
    # fully-compiled programs (incl. this pool's jitted multi-row admit)
    # budget = chunk + a full batch of shorts: bounds the per-step
    # prefill stall without starving free slots while a long is chunking
    # the measured arm carries the full efficiency stack: XLA cost-model
    # harvest (compiles land in warm_arm, where account() first sees each
    # program), SLO digests with deliberately generous targets — this row
    # gates that goodput is MEASURED sanely, not that a CPU box meets a
    # production SLO — and the default flight recorder
    arm_sf = ServingEngine(engine, num_slots=slots, max_queue_depth=n_req,
                           prefill_chunk=chunk,
                           prefill_token_budget=2 * chunk + 64 * slots,
                           cost_model=True,
                           slo={"ttft_ms": 120_000.0, "gap_ms": 2_000.0,
                                "window_steps": 64})
    arm_serial = ServingEngine(engine, num_slots=slots,
                               max_queue_depth=n_req, prefill_chunk=0)
    assert arm_sf._stall_free and not arm_serial._stall_free
    warm_arm(arm_sf)
    warm_arm(arm_serial)
    # both arms fully warmed: the runtime watchdogs now count any cache
    # growth as a real recompile (both watch the SHARED engine jits, so
    # max() rather than sum() avoids double-counting those)
    arm_sf.end_warmup()
    arm_serial.end_warmup()
    if _SIGNATURES_PATH:
        extra = {"vocab_size": cfg.vocab_size, "max_prompt_len": long_hi}
        arm_sf.export_signatures(_SIGNATURES_PATH, merge=True, extra=extra)
        arm_serial.export_signatures(_SIGNATURES_PATH, merge=True,
                                     extra=extra)
    n_decode_programs = engine._jit_decode._cache_size()

    # interleaved replications with per-metric medians: single CPU
    # replays jitter ~10% run-to-run, enough to flip a close verdict
    reps = 3
    sf_runs, serial_runs = [], []
    for _ in range(reps):
        sf_runs.append(run_arm(arm_sf, timed=True))
        serial_runs.append(run_arm(arm_serial, timed=True))
    # efficiency rollup for the LAST stall-free replication (the window
    # resets per rep); must precede the traced replay, which resets again
    eff = arm_sf.efficiency_snapshot()

    decode_recompiles = engine._jit_decode._cache_size() - n_decode_programs
    recompiles = max(arm_sf.watchdog.recompiles,
                     arm_serial.watchdog.recompiles)
    # greedy: outputs must be bitwise identical across arms AND reps
    # (admission grouping varies with timing; results must not)
    parity = all(r["outputs"] == serial_runs[0]["outputs"]
                 for r in sf_runs + serial_runs)

    tracer_detail = None
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        arm_sf.set_tracer(Tracer())
        run_arm(arm_sf, timed=True)     # traced replay on the warmed arm
        n_events = arm_sf.tracer.export(_TRACE_PATH)
        tracer_detail = {"path": _TRACE_PATH, "events": n_events}

    _MED_KEYS = ("requests_per_s", "tokens_per_s", "ttft_p50_ms",
                 "ttft_p99_ms", "per_token_p50_ms", "per_token_p99_ms",
                 "step_gap_p50_ms", "step_gap_p99_ms", "stall_time_s")

    def _median(runs):
        out = dict(runs[-1])
        for k in _MED_KEYS:
            out[k] = float(np.median([r[k] for r in runs]))
        return out

    sf, serial = _median(sf_runs), _median(serial_runs)

    def arm_detail(s):
        return {"requests_per_s": round(s["requests_per_s"], 3),
                "tokens_per_s": round(s["tokens_per_s"], 1),
                "ttft_p50_ms": round(s["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(s["ttft_p99_ms"], 1),
                "per_token_p50_ms": round(s["per_token_p50_ms"], 2),
                "per_token_p99_ms": round(s["per_token_p99_ms"], 2),
                "step_gap_p50_ms": round(s["step_gap_p50_ms"], 2),
                "step_gap_p99_ms": round(s["step_gap_p99_ms"], 2),
                "prefill_dispatches": s["prefill_dispatches"],
                "stall_time_s": round(s["stall_time_s"], 4),
                "completed": s["completed"]}

    _emit({
        "metric": f"stall-free serving admission (chunk {chunk}, "
                  f"{n_req} req @ {rate}/s, {slots} slots, short "
                  f"{len_lo}-{len_hi} / long {long_lo}-{long_hi} prompts): "
                  f"p99 inter-token gap",
        "value": round(sf["step_gap_p99_ms"], 2),
        "unit": "ms (lower is better)",
        "vs_baseline": round(serial["step_gap_p99_ms"] /
                             max(sf["step_gap_p99_ms"], 1e-9), 3),
        "detail": {
            "baseline": "serial whole-prompt admission (prefill_chunk=0) "
                        "at equal slots/policy — the PR-2 discipline on "
                        "the same engine and kernels. vs_baseline is the "
                        "serial arm's p99 inter-token gap over the "
                        "stall-free arm's (>1: the tail shrank)",
            "greedy_parity": bool(parity),
            "decode_recompiles_after_warmup": int(decode_recompiles),
            "recompiles_after_warmup": int(recompiles),
            "tracer": tracer_detail,
            "replications": reps,
            "efficiency": {
                "mfu": round(eff.get("mfu") or 0.0, 6),
                "bandwidth_util": round(
                    eff.get("bandwidth_util") or 0.0, 6),
                "hbm_peak_bytes": eff.get("hbm_peak_bytes"),
                "hbm_drift": eff.get("hbm_drift"),
                "goodput_slo": round(eff.get("goodput_slo") or 0.0, 4),
                "slo_ttft_p99_ms": round(eff.get("ttft_p99_ms") or 0.0, 1),
                "slo_gap_p99_ms": round(eff.get("gap_p99_ms") or 0.0, 2),
                "alert_state": eff.get("alert_state"),
                "overhead_pct": round(eff.get("overhead_pct") or 0.0, 3),
                "cost_model_unavailable":
                    eff["costs"]["unavailable"] if "costs" in eff else None,
            },
            "ttft_p99_ratio": round(serial["ttft_p99_ms"] /
                                    max(sf["ttft_p99_ms"], 1e-9), 3),
            "stall_free": arm_detail(sf),
            "serial": arm_detail(serial),
        },
    })


def spec_main():
    """Speculative-decoding serving row: n-gram draft + verify_k vs plain
    one-token decode — same engine, slots and workload; the only change
    is the ``spec_decode`` block."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        n_req, slots, k = 16, 4, 6
        len_lo, len_hi, gen_lo, gen_hi = 16, 48, 32, 96
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots, k = 32, 8, 8
        len_lo, len_hi, gen_lo, gen_hi = 32, 128, 64, 224

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    # repetitive-text workload — prompt-lookup drafting's home turf
    # (summarization/code-edit/retrieval-style traffic that quotes its
    # own context): each prompt tiles a short random motif
    prompts, budgets = [], []
    for _ in range(n_req):
        T = int(gen.integers(len_lo, len_hi + 1))
        motif = gen.integers(0, cfg.vocab_size,
                             size=int(gen.integers(4, 9)))
        prompts.append(np.tile(motif, T // len(motif) + 1)[:T]
                       .astype(np.int32))
        budgets.append(int(gen.integers(gen_lo, gen_hi + 1)))

    spec_cfg = {"drafter": "ngram", "k": k, "max_ngram": 3}

    def run_arm(spec):
        srv = ServingEngine(engine, num_slots=slots, max_queue_depth=n_req,
                            spec_decode=spec)
        for p, b in zip(prompts, budgets):
            srv.submit(p, max_new_tokens=b)
        t0 = time.perf_counter()
        done = srv.run_until_drained()
        wall = time.perf_counter() - t0
        s = srv.stats()
        s["wall_s"] = wall
        s["outputs"] = {r.request_id % n_req: list(r.output_tokens)
                        for r in done}
        return s, srv

    run_arm(None), run_arm(spec_cfg)       # warmup: compile both arms
    plain, _ = run_arm(None)
    spec, srv_spec = run_arm(spec_cfg)

    # post-run recompile probe (+ traced replay when --trace is given):
    # the spec arm's server is fully exercised, so a warm replay of the
    # workload must not grow any executable cache
    srv_spec.end_warmup()
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        srv_spec.set_tracer(Tracer())
    for p, b in zip(prompts, budgets):
        srv_spec.submit(p, max_new_tokens=b)
    srv_spec.run_until_drained()
    tracer_detail = None
    if _TRACE_PATH:
        tracer_detail = {"path": _TRACE_PATH,
                         "events": srv_spec.tracer.export(_TRACE_PATH)}
    recompiles = srv_spec.watchdog.recompiles

    parity = plain["outputs"] == spec["outputs"]  # greedy: must be bitwise
    tps_plain = plain["new_tokens"] / plain["wall_s"]
    tps_spec = spec["new_tokens"] / spec["wall_s"]

    _emit({
        "metric": f"speculative decoding (ngram k={k}) on repetitive-text "
                  f"serving ({n_req} req, {slots} slots, prompts "
                  f"{len_lo}-{len_hi}, budgets {gen_lo}-{gen_hi})",
        "value": round(spec["tokens_per_decode_step"], 3),
        "unit": "tokens/slot-decode-step",
        "vs_baseline": round(tps_spec / tps_plain, 3),
        "detail": {
            "baseline": "plain one-token decode, same engine/slots/"
                        "workload (tokens_per_decode_step == 1.0 by "
                        "construction)",
            "greedy_parity": bool(parity),
            "recompiles_after_warmup": int(recompiles),
            "tracer": tracer_detail,
            "acceptance_rate": round(spec["spec_acceptance_rate"], 3)
            if spec["spec_acceptance_rate"] is not None else None,
            "draft_overhead_pct": round(spec["draft_overhead_pct"], 2)
            if spec["draft_overhead_pct"] is not None else None,
            "spec": {
                "tokens_per_s": round(tps_spec, 1),
                "tokens_per_decode_step": round(
                    spec["tokens_per_decode_step"], 3),
                "decode_steps": spec["decode_steps"],
                "drafted": spec["spec_drafted"],
                "accepted": spec["spec_accepted"],
                "ttft_p50_ms": round(spec["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(spec["ttft_p99_ms"], 1),
            },
            "plain": {
                "tokens_per_s": round(tps_plain, 1),
                "tokens_per_decode_step": round(
                    plain["tokens_per_decode_step"], 3),
                "decode_steps": plain["decode_steps"],
                "ttft_p50_ms": round(plain["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(plain["ttft_p99_ms"], 1),
            },
        },
    })


def paging_main():
    """Paged-KV row: the SAME ≥50%-shared-prefix workload driven through
    a contiguous-SlotPool server and a PagedKVPool server given the SAME
    KV HBM budget (``slots_c * capacity == num_pages * page_size``), but
    the paged arm runs 2x the slots — prefix sharing dedupes the common
    pages, so more requests fit in the same memory. Reports peak resident
    requests at equal HBM (the headline), served requests per KV-GB,
    TTFT cold vs prefix-hit, prefix hit rate, CoW forks, peak pages in
    use, and the zero-recompile gate after a warm replay; greedy outputs
    must be bitwise identical across both arms."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        n_req, slots_c, ps = 16, 4, 32
        pre_len, suf_lo, suf_hi = 96, 8, 32       # shared prefix: 3 pages
        dup_len, gen_lo, gen_hi = 128, 16, 32     # dup: 4 FULL pages (CoW)
        cold_lo, cold_hi = 32, 64
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots_c, ps = 32, 8, 64
        pre_len, suf_lo, suf_hi = 256, 32, 128
        dup_len, gen_lo, gen_hi = 512, 64, 128
        cold_lo, cold_hi = 64, 256
    slots_p = 2 * slots_c
    num_pages = slots_c * cfg.max_seq_len // ps   # EQUAL KV bytes by
    #                                               construction

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    shared = gen.integers(0, cfg.vocab_size, size=pre_len).astype(np.int32)
    dup = gen.integers(0, cfg.vocab_size, size=dup_len).astype(np.int32)
    prompts, budgets = [], []
    for i in range(n_req):
        if i < 2:         # page-aligned exact duplicates: full hit -> CoW
            prompts.append(dup.copy())
        elif i < n_req - n_req // 4:   # shared prefix + unique suffix
            suf = gen.integers(0, cfg.vocab_size,
                               size=int(gen.integers(suf_lo, suf_hi + 1)))
            prompts.append(np.concatenate([shared, suf]).astype(np.int32))
        else:             # cold random tail (~25%)
            prompts.append(gen.integers(
                0, cfg.vocab_size,
                size=int(gen.integers(cold_lo, cold_hi + 1)))
                .astype(np.int32))
        budgets.append(int(gen.integers(gen_lo, gen_hi + 1)))
    # leaders = [dup, first shared]; the second duplicate rides in the
    # burst so its full hit (and the CoW fork it forces) lands under load
    prompts[1], prompts[2] = prompts[2], prompts[1]
    budgets[1], budgets[2] = budgets[2], budgets[1]

    def make_srv(paged: bool) -> ServingEngine:
        # the measured (paged) arm also carries the cost model so the row
        # can gate page-math-predicted KV HBM == actual device bytes
        return ServingEngine(
            engine, num_slots=slots_p if paged else slots_c,
            max_queue_depth=2 * n_req, prefill_chunk=ps,
            preempt_queue_threshold=n_req // 2,
            cost_model=paged,
            slo={"ttft_ms": 120_000.0, "gap_ms": 2_000.0,
                 "window_steps": 64} if paged else None,
            paged_kv={"page_size": ps, "num_pages": num_pages}
            if paged else False)

    def kv_bytes(pool) -> int:
        cs = pool.cache["cache_store"]
        return sum(int(np.prod(cs[k].shape)) * cs[k].dtype.itemsize
                   for k in ("k", "v"))

    def run_arm(srv: ServingEngine, paged: bool) -> dict:
        # compile this server's programs on prompts DISJOINT from the
        # workload (the trie must stay cold for the measured run) by
        # sweeping every admission grouping the static checker
        # enumerates — each singleton width bucket up to the chunk,
        # each (rows x width) group the prefill token budget allows,
        # and one chunk-looped long prefill — not just the shapes this
        # workload's length mix happens to hit. A distinct leading
        # token per warm prompt keeps the sweep from prefix-hitting
        # itself, so every entry drives the cold admission path it is
        # meant to compile.
        tok = 0

        def warm(w: int, count: int) -> None:
            nonlocal tok
            for _ in range(count):
                tok += 1
                srv.submit(np.full((w,), tok, np.int32), max_new_tokens=2)
            srv.run_until_drained()

        slots = slots_p if paged else slots_c
        budget = 2 * ps   # the ServingEngine default this row runs with
        w = 16
        while w <= ps:
            for count in range(1, min(slots, max(1, budget // w)) + 1):
                warm(w, count)
            w *= 2
        warm(4 * ps, 1)   # long prefill: drives the chunk loop
        srv.reset_efficiency_window()   # efficiency covers the timed drain
        peak_live = peak_pages = guard = 0
        t0 = time.perf_counter()

        def drain():
            nonlocal peak_live, peak_pages, guard
            while srv.pending or srv.live_count:
                srv.step()
                peak_live = max(peak_live, srv.live_count)
                if paged:
                    peak_pages = max(peak_pages, srv.pool.num_pages
                                     - srv.pool.free_page_count)
                guard += 1
                assert guard < 20_000, "paging drain did not terminate"

        # leaders first (one duplicate, one shared-prefix request) so the
        # trie is warm when the burst lands — the realistic steady state,
        # where earlier traffic has already published the hot prefixes
        reqs = [srv.submit(p, max_new_tokens=b)
                for p, b in zip(prompts[:2], budgets[:2])]
        drain()
        reqs += [srv.submit(p, max_new_tokens=b)
                 for p, b in zip(prompts[2:], budgets[2:])]
        drain()
        wall = time.perf_counter() - t0
        srv.check_invariants()
        s = srv.stats()
        s["wall_s"] = wall
        s["peak_live"] = peak_live
        s["peak_pages"] = peak_pages
        s["kv_gb"] = kv_bytes(srv.pool) / 2**30
        s["outputs"] = [list(r.output_tokens) for r in reqs]
        # prefill latency (admit -> first token), NOT submit-based TTFT:
        # under an all-at-once burst queueing dominates submit-based
        # numbers, hiding the prefill work the prefix cache skips
        lat = [(r.prefix_hit_tokens, r.first_token_time - r.admit_time)
               for r in reqs]
        s["prefill_cold_ms"] = 1e3 * float(np.median(
            [t for h, t in lat if h == 0]))
        hits = [t for h, t in lat if h > 0]
        s["prefill_hit_ms"] = 1e3 * float(np.median(hits)) if hits else None
        s["n_prefix_hit_reqs"] = len(hits)
        return s

    srv_paged = make_srv(paged=True)
    srv_dense = make_srv(paged=False)
    dense = run_arm(srv_dense, paged=False)
    paged = run_arm(srv_paged, paged=True)
    # page-math-predicted KV bytes vs actual device bytes must agree
    # EXACTLY (drift 0.0) — taken before the warm replay below
    eff = srv_paged.efficiency_snapshot()

    # zero-recompile gate: warm replay of the whole workload (now ALL
    # prefix hits, including the CoW forks the duplicates force) on the
    # measured paged server must not grow any executable cache
    srv_paged.end_warmup()
    if _SIGNATURES_PATH:
        # the manifest freezes at end_warmup: everything up to and
        # including the measured run is warmup-eligible traffic the
        # static enumeration must cover; the warm replay below is the
        # post-warmup phase the invariant protects
        extra = {"vocab_size": cfg.vocab_size,
                 "max_seed_len": dup_len + gen_hi}
        srv_paged.export_signatures(_SIGNATURES_PATH, merge=True,
                                    extra=extra)
        srv_dense.export_signatures(_SIGNATURES_PATH, merge=True,
                                    extra=extra)
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        srv_paged.set_tracer(Tracer())
    for p, b in zip(prompts, budgets):
        srv_paged.submit(p, max_new_tokens=b)
    srv_paged.run_until_drained(max_steps=20_000)
    tracer_detail = None
    if _TRACE_PATH:
        tracer_detail = {"path": _TRACE_PATH,
                         "events": srv_paged.tracer.export(_TRACE_PATH)}
    recompiles = srv_paged.watchdog.recompiles
    pstats = srv_paged.pool.page_stats()

    parity = dense["outputs"] == paged["outputs"]  # greedy: must be bitwise
    resident_ratio = paged["peak_live"] / max(dense["peak_live"], 1)

    _emit({
        "metric": f"paged KV + prefix cache vs contiguous slots at EQUAL "
                  f"KV HBM ({n_req} req, >=50% shared prefix, "
                  f"{slots_c}->{slots_p} slots, {num_pages} pages x {ps}): "
                  f"peak resident requests ratio",
        "value": round(resident_ratio, 3),
        "unit": "resident-requests ratio at equal KV HBM (higher is "
                "better)",
        "vs_baseline": round(resident_ratio, 3),
        "detail": {
            "baseline": "contiguous SlotPool, same engine/workload/"
                        "chunked admission; the paged arm holds the same "
                        "KV bytes (num_pages*page_size == slots*capacity) "
                        "but seats 2x the slots — shared-prefix pages are "
                        "mapped, not copied, so the extra slots are real "
                        "concurrency, not extra memory",
            "greedy_parity": bool(parity),
            "recompiles_after_warmup": int(recompiles),
            "tracer": tracer_detail,
            "prefix_hit_rate": round(paged["prefix_hit_rate"], 3),
            "n_prefix_hit_reqs": paged["n_prefix_hit_reqs"],
            "prefill_cold_ms": round(paged["prefill_cold_ms"], 1),
            "prefill_hit_ms": round(paged["prefill_hit_ms"], 1)
            if paged["prefill_hit_ms"] is not None else None,
            "cow_copies": pstats["cow_copies"],
            "page_evictions": pstats["page_evictions"],
            "preempted": paged["preempted"],
            "efficiency": {
                "mfu": round(eff.get("mfu") or 0.0, 6),
                "hbm_peak_bytes": eff.get("hbm_peak_bytes"),
                "hbm_drift": eff.get("hbm_drift"),
                "kv_bytes_predicted":
                    eff["costs"]["hbm"].get("kv_bytes_predicted")
                    if "costs" in eff else None,
                "kv_bytes_actual":
                    eff["costs"]["hbm"].get("kv_bytes_actual")
                    if "costs" in eff else None,
                "goodput_slo": round(eff.get("goodput_slo") or 0.0, 4),
                "overhead_pct": round(eff.get("overhead_pct") or 0.0, 3),
            },
            "paged": {
                "peak_resident_requests": paged["peak_live"],
                "served_per_kv_gb": round(
                    paged["completed"] / paged["kv_gb"], 1),
                "peak_pages_in_use": paged["peak_pages"],
                "pages_total": num_pages,
                "requests_per_s": round(
                    paged["completed"] / paged["wall_s"], 2),
                "ttft_p50_ms": round(paged["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(paged["ttft_p99_ms"], 1),
            },
            "contiguous": {
                "peak_resident_requests": dense["peak_live"],
                "served_per_kv_gb": round(
                    dense["completed"] / dense["kv_gb"], 1),
                "requests_per_s": round(
                    dense["completed"] / dense["wall_s"], 2),
                "ttft_p50_ms": round(dense["ttft_p50_ms"], 1),
                "ttft_p99_ms": round(dense["ttft_p99_ms"], 1),
            },
        },
    })


def serving_tp_main():
    """Multi-chip serving row: (data, model)-mesh sharded engines plus
    the data-parallel replica router, on the forced 8-device CPU host.

    Three arm families on one model/workload family:

    * **TP=1** (mesh ``data=8, model=1``) and **TP=2** (``data=4,
      model=2``): the same stall-free dense-slot serving config on two
      mesh shapes. Greedy outputs must be BITWISE identical across the
      two meshes and across replications (the tentpole parity
      invariant), and neither arm may recompile after warmup (the jit
      signatures are mesh-shape-independent; only shardings move).
    * **DP=2 router**: a :class:`ReplicaRouter` over two paged replicas
      on DISJOINT 4-device meshes vs ONE identically-configured paged
      replica, on a 4-session-group workload whose prefixes cannot all
      fit in one replica's page pool. Session affinity keeps each
      group's prefix resident on its home replica while the single
      replica thrashes (evicts and re-prefills) — the skipped prefill
      chunks are the aggregate-throughput win the headline gates
      (``vs_baseline`` = router req/s over single-replica req/s,
      ``check_regression.py --threshold 1.5``).

    Example::

        python bench.py serving-tp --json BENCH_serving_tp.json \\
            --signatures signatures.json
        python check_regression.py BENCH_serving_tp.json \\
            BENCH_serving_tp.json --threshold 1.5 --max-recompiles 0 \\
            --require-zero-leaks --signatures-json signatures.json \\
            --require-signature-match

    The row also carries the zero-leak / invariant / timeline gates
    (``--require-zero-leaks``) summed over ALL five servers, and every
    arm merge-unions its warmup manifest into ``--signatures`` for the
    ``--require-signature-match`` gate.
    """
    import os

    # Both env vars must land BEFORE the first jax import in this
    # process: XLA_FLAGS is read once at backend initialization
    # (exporting it later is a silent no-op and every mesh axis comes up
    # size 1), and JAX_PLATFORMS=cpu must ride along or an accelerator
    # plugin force-selects itself and the forced cpu devices never
    # exist. Same interaction tests/conftest.py::tp_mesh documents.
    _flag = "--xla_force_host_platform_device_count=8"
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = \
            (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.serving import ReplicaRouter, ServingEngine
    from deepspeed_tpu.serving.metrics import ServingMetrics

    cfg = TransformerConfig(vocab_size=512, max_seq_len=1024, n_embd=128,
                            n_layer=4, n_head=4, dtype=jnp.float32)
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    devs = jax.devices()
    if len(devs) < 8:
        raise RuntimeError(
            f"serving-tp needs the forced 8-device host ({len(devs)} "
            f"visible) — was jax imported before this row set XLA_FLAGS?")

    def make_engine(devices, data, model_ax):
        # serving reads the global mesh at CONSTRUCTION time only, so
        # installing each engine's mesh just before building it (and its
        # server) is sufficient — replicas on disjoint meshes then step
        # concurrently without touching the global registry
        mesh = mesh_mod.build_mesh(devices=devices, data=data,
                                   model=model_ax)
        mesh_mod.set_mesh(mesh)
        return ds.init_inference(model, model_parameters=params,
                                 dtype="fp32", mesh=mesh)

    gen = np.random.default_rng(0)

    # -- tensor-parallel arms (dense slots, stall-free admission) ------
    slots_tp, chunk = 8, 256
    budget_tp = 2 * chunk + 64 * slots_tp
    n_tp, long_hi = 24, 512
    tp_prompts, tp_budgets = [], []
    for i in range(n_tp):
        T = int(gen.integers(300, 500)) if i % 6 == 5 \
            else int(gen.integers(17, 33))
        tp_prompts.append(gen.integers(0, cfg.vocab_size, size=T)
                          .astype(np.int32))
        tp_budgets.append(int(gen.integers(8, 17)))

    def make_tp(data, model_ax):
        eng = make_engine(devs, data, model_ax)
        return ServingEngine(eng, num_slots=slots_tp,
                             max_queue_depth=2 * n_tp,
                             prefill_chunk=chunk,
                             prefill_token_budget=budget_tp,
                             strict_recompile=True)

    def warm_tp(srv):
        # stall-row discipline: every admission grouping the static
        # checker enumerates — singleton width buckets up to the chunk,
        # each (rows x width) group the token budget allows, one
        # chunk-looped long prefill — then arm the watchdog
        w = 16
        while w <= chunk:
            for count in range(1, min(slots_tp,
                                      max(1, budget_tp // w)) + 1):
                for _ in range(count):
                    srv.submit(np.ones((w,), np.int32), max_new_tokens=2)
                srv.run_until_drained()
            w *= 2
        srv.submit(np.ones((long_hi,), np.int32), max_new_tokens=2)
        srv.run_until_drained()
        srv.end_warmup()

    def run_tp(srv):
        # fresh aggregates per replication; warmup and earlier reps
        # polluted the percentile digests
        srv.metrics = ServingMetrics(None, registry=srv.registry,
                                     step_fn=lambda s=srv: s.step_id)
        t0 = time.perf_counter()
        reqs = [srv.submit(p, max_new_tokens=b)
                for p, b in zip(tp_prompts, tp_budgets)]
        srv.run_until_drained(max_steps=50_000)
        wall = time.perf_counter() - t0
        s = srv.stats()
        s["wall_s"] = wall
        s["outputs"] = [list(r.output_tokens) for r in reqs]
        return s

    tp1 = make_tp(data=8, model_ax=1)
    warm_tp(tp1)
    tp2 = make_tp(data=4, model_ax=2)
    warm_tp(tp2)

    # -- data-parallel router arms (paged KV, session affinity) --------
    # geometry chosen so ONE replica's page pool cannot hold all four
    # session groups' prefixes (4 x 8 pages + working set > 24 pages)
    # while each router replica CAN hold its own two (2 x 8 + working
    # set < 24): the single replica thrashes, the router does not
    ps, prefix_pages, n_groups = 32, 8, 4
    prefix_len = prefix_pages * ps
    slots_dp, num_pages, n_dp, gen_dp = 2, 24, 32, 8
    budget_dp = 2 * ps + 16 * slots_dp
    prefixes = {g: gen.integers(1, cfg.vocab_size, size=prefix_len)
                .astype(np.int32) for g in range(n_groups)}
    dp_reqs = []
    for i in range(n_dp):
        g = i % n_groups   # strict group cycling: the LRU-worst order
        suf = gen.integers(1, cfg.vocab_size,
                           size=int(gen.integers(4, 12))).astype(np.int32)
        dp_reqs.append((str(g), np.concatenate([prefixes[g], suf])))

    def make_dp(devices):
        eng = make_engine(devices, data=len(devices), model_ax=1)
        return ServingEngine(eng, num_slots=slots_dp,
                             max_queue_depth=2 * n_dp,
                             prefill_chunk=ps,
                             prefill_token_budget=budget_dp,
                             strict_recompile=True,
                             paged_kv={"page_size": ps,
                                       "num_pages": num_pages})

    def warm_dp(srv):
        # same sweep as the paging row: distinct leading tokens keep
        # the warm prompts from prefix-hitting themselves
        tok = 0

        def warm(w, count):
            nonlocal tok
            for _ in range(count):
                tok += 1
                srv.submit(np.full((w,), tok, np.int32), max_new_tokens=2)
            srv.run_until_drained()

        w = 16
        while w <= ps:
            for count in range(1, min(slots_dp,
                                      max(1, budget_dp // w)) + 1):
                warm(w, count)
            w *= 2
        warm(prefix_len + 16, 1)   # chunk-loop long prefill
        # page-aligned exact duplicate: the full-page hit + decode
        # forces the copy-on-write page copy, the one paged program the
        # distinct-token sweep above can never reach
        dup = np.full((2 * ps,), cfg.vocab_size - 3, np.int32)
        for _ in range(2):
            srv.submit(dup, max_new_tokens=2)
            srv.run_until_drained()
        srv.end_warmup()

    single = make_dp(devs[:4])
    warm_dp(single)
    rep_a = make_dp(devs[:4])
    warm_dp(rep_a)
    rep_b = make_dp(devs[4:])
    warm_dp(rep_b)
    router = ReplicaRouter([rep_a, rep_b])

    if _SIGNATURES_PATH:
        extra_tp = {"vocab_size": cfg.vocab_size, "max_prompt_len": long_hi}
        extra_dp = {"vocab_size": cfg.vocab_size,
                    "max_seed_len": prefix_len + 16 + gen_dp}
        tp1.export_signatures(_SIGNATURES_PATH, merge=True, extra=extra_tp)
        tp2.export_signatures(_SIGNATURES_PATH, merge=True, extra=extra_tp)
        for srv in (single, rep_a, rep_b):
            srv.export_signatures(_SIGNATURES_PATH, merge=True,
                                  extra=extra_dp)

    def run_dp(target, use_session):
        t0 = time.perf_counter()
        reqs = []
        for sess, prompt in dp_reqs:
            kw = {"session": sess} if use_session else {}
            reqs.append(target.submit(prompt, max_new_tokens=gen_dp, **kw))
        target.run_until_drained(max_steps=100_000)
        wall = time.perf_counter() - t0
        return {"requests_per_s": n_dp / wall,
                "outputs": [list(r.output_tokens) for r in reqs]}

    # interleaved replications with per-metric medians (single-CPU
    # replays jitter enough to flip a close verdict); every arm is
    # fully warmed, so the strict watchdogs police the whole timed
    # phase — any recompile here raises at the step boundary
    reps = 3
    tp1_runs, tp2_runs, single_runs, router_runs = [], [], [], []
    for _ in range(reps):
        tp1_runs.append(run_tp(tp1))
        tp2_runs.append(run_tp(tp2))
        single_runs.append(run_dp(single, use_session=False))
        router_runs.append(run_dp(router, use_session=True))

    def _med(runs, key):
        return float(np.median([r[key] for r in runs]))

    tp_parity = all(r["outputs"] == tp1_runs[0]["outputs"]
                    for r in tp1_runs + tp2_runs)
    dp_parity = all(r["outputs"] == single_runs[0]["outputs"]
                    for r in single_runs + router_runs)
    single_rps = _med(single_runs, "requests_per_s")
    router_rps = _med(router_runs, "requests_per_s")
    dp_ratio = router_rps / max(single_rps, 1e-9)

    servers = [tp1, tp2, single, rep_a, rep_b]
    recompiles = (tp1.watchdog.recompiles + tp2.watchdog.recompiles
                  + single.watchdog.recompiles + router.recompiles)
    leaks = sum(s.pool.num_slots - s.pool.free_count - s.live_count
                for s in servers)
    invariants_ok = True
    try:
        for s in servers[:3]:
            s.check_invariants()
        router.check_invariants()
    except Exception:
        invariants_ok = False
    open_tl = [rid for s in servers for rid in s.timelines.open_ids()]
    timelines_complete = not open_tl

    sstats = single.stats()["paging"]
    astats = rep_a.stats()["paging"]
    bstats = rep_b.stats()["paging"]
    rstats = router.stats()

    def tp_detail(runs, srv):
        s = runs[-1]
        return {"requests_per_s": round(_med(runs, "requests_per_s"), 3),
                "per_token_p50_ms": round(_med(runs, "per_token_p50_ms"),
                                          2),
                "per_token_p99_ms": round(_med(runs, "per_token_p99_ms"),
                                          2),
                "step_gap_p99_ms": round(_med(runs, "step_gap_p99_ms"), 2),
                "completed": s["completed"],
                "mesh": {"data": srv._mesh_axis_size("data"),
                         "model": srv._mesh_axis_size("model")}}

    _emit({
        "metric": f"multi-chip serving ((data,model) mesh + DP router, "
                  f"forced 8-device host; DP: {n_groups} session groups "
                  f"x {prefix_pages}-page prefixes over {num_pages}-page "
                  f"pools): router req/s over single replica",
        "value": round(dp_ratio, 3),
        "unit": "aggregate req/s ratio (higher is better)",
        "vs_baseline": round(dp_ratio, 3),
        "detail": {
            "baseline": "ONE paged replica with the identical serving "
                        "config and page pool, same workload without "
                        "session routing — its pool cannot hold every "
                        "group's prefix, so admissions thrash the trie "
                        "(evict + re-prefill) where the router's "
                        "session affinity keeps each group's prefix "
                        "resident on its home replica",
            "greedy_parity_tp": bool(tp_parity),
            "greedy_parity_dp": bool(dp_parity),
            "recompiles_after_warmup": int(recompiles),
            "slot_leaks": int(leaks),
            "invariants_ok": bool(invariants_ok),
            "timelines_complete": bool(timelines_complete),
            "replications": reps,
            "tp1": tp_detail(tp1_runs, tp1),
            "tp2": tp_detail(tp2_runs, tp2),
            "dp": {
                "single_requests_per_s": round(single_rps, 3),
                "router_requests_per_s": round(router_rps, 3),
                "single_page_evictions": sstats["page_evictions"],
                "single_prefix_hits": sstats["prefix_hits"],
                "single_prefix_misses": sstats["prefix_misses"],
                "replica_page_evictions": [astats["page_evictions"],
                                           bstats["page_evictions"]],
                "replica_prefix_hits": [astats["prefix_hits"],
                                        bstats["prefix_hits"]],
                "replica_prefix_misses": [astats["prefix_misses"],
                                          bstats["prefix_misses"]],
                "router": {"dispatched": rstats["dispatched"],
                           "affinity_hits": rstats["affinity_hits"],
                           "spills": rstats["spills"],
                           "failovers": rstats["failovers"]},
            },
        },
    })


def serving_disagg_main():
    """Disaggregated prefill/decode row: a 1-prefill + 1-decode fleet
    (cross-pool page transfer handoffs) vs a colocated DP=2 router at
    EQUAL device count (two disjoint 4-device meshes each), on the
    forced 8-device CPU host.

    The workload is prefill-HEAVY Poisson traffic (long multi-page
    prompts, short decode budgets, seeded arrivals): on a colocated
    replica every admission chunk runs inside a step that decoding
    requests are waiting through, so prefill interference lands
    directly in the inter-token gap tail. The disaggregated decode
    replica never prefills — its steps are pure decode — which is the
    DistServe/Splitwise claim this row pins. Headline ``value`` is the
    disaggregated arm's decode step-gap p99 (gaps recorded on
    decode-capable replicas only); ``vs_baseline`` is the colocated
    arm's over it (>1: disaggregation shrank the decode tail).

    Both arms run strict recompile watchdogs the whole timed phase, the
    warmup drives real transfers through the fleet BEFORE end_warmup so
    the transfer program's signature lands in the manifest, and greedy
    outputs must be bitwise identical across arms and replications (a
    transferred page is the exact bits the prefill replica wrote).
    ``detail.prefix`` pins the global-prefix-awareness lift: handoffs
    routed via the shared first-page index and the transfer pages a
    destination trie hit kept off the wire.

    ``detail.journeys`` / ``detail.transfer_latency_p99_ms`` /
    ``detail.efficiency`` report the fleet observability plane over the
    disaggregated arm: cross-replica journey completeness (every
    terminal journey stitches with all homes closed), the merged
    per-transfer latency tail, fleet goodput and the instrumentation
    overhead as a fraction of accumulated step wall.

    Example::

        python bench.py serving-disagg --json BENCH_serving_disagg.json \\
            --signatures signatures.json
        python check_regression.py BENCH_serving_disagg.json \\
            BENCH_serving_disagg.json --metric value:lower \\
            --max-overhead-pct 3 --require-complete-journeys \\
            --max-recompiles 0 --require-zero-leaks \\
            --signatures-json signatures.json --require-signature-match
    """
    import os

    # must land before the first jax import (see serving_tp_main)
    _flag = "--xla_force_host_platform_device_count=8"
    if _flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = \
            (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.serving import ReplicaRouter, ServingEngine
    from deepspeed_tpu.serving.metrics import ServingMetrics

    cfg = TransformerConfig(vocab_size=512, max_seq_len=512, n_embd=128,
                            n_layer=4, n_head=4, dtype=jnp.float32)
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    devs = jax.devices()
    if len(devs) < 8:
        raise RuntimeError(
            f"serving-disagg needs the forced 8-device host ({len(devs)} "
            f"visible) — was jax imported before this row set XLA_FLAGS?")

    def make_engine(devices):
        mesh = mesh_mod.build_mesh(devices=devices, data=len(devices),
                                   model=1)
        mesh_mod.set_mesh(mesh)
        return ds.init_inference(model, model_parameters=params,
                                 dtype="fp32", mesh=mesh)

    # -- workload: prefill-heavy, seeded Poisson arrivals --------------
    # long multi-page prompts (2-3 pages, chunk-looped prefill), short
    # decode budgets; a quarter of the traffic shares per-group
    # first-page prefixes so the shared first-page index has something
    # to route on (and the colocated arm's tries get the same benefit)
    gen = np.random.default_rng(0)
    ps, slots, num_pages = 32, 4, 96
    n_req, n_groups = 24, 4
    budget = 2 * ps + 16 * slots
    group_prefix = {g: gen.integers(1, cfg.vocab_size, size=ps)
                    .astype(np.int32) for g in range(n_groups)}

    def make_workload(seed):
        wrng = np.random.default_rng(seed)
        prompts, budgets, sessions = [], [], []
        for i in range(n_req):
            n = int(wrng.integers(ps + 1, 3 * ps))
            body = wrng.integers(1, cfg.vocab_size, size=n).astype(np.int32)
            if i % 4 == 0:          # grouped: shared first page
                g = (i // 4) % n_groups
                body[:ps] = group_prefix[g]
                sessions.append(str(g))
            else:
                sessions.append(None)
            prompts.append(body)
            budgets.append(int(wrng.integers(4, 9)))
        return prompts, budgets, sessions

    prompts, budgets, sessions = make_workload(7)
    # Poisson arrivals in router-step units — identical schedule for
    # both arms, sustained enough that admissions overlap live decode
    arrivals = []
    t = 0
    arr_rng = np.random.default_rng(11)
    for _ in range(n_req):
        arrivals.append(t)
        t += int(arr_rng.poisson(1.0))

    def make_srv(devices, role):
        eng = make_engine(devices)
        return ServingEngine(eng, num_slots=slots,
                             max_queue_depth=2 * n_req, prefill_chunk=ps,
                             prefill_token_budget=budget,
                             strict_recompile=True, role=role, slo=True,
                             paged_kv={"page_size": ps,
                                       "num_pages": num_pages})

    def warm_admitting(srv):
        """The paging-row width sweep on a replica that can finish work
        (role 'both' or 'decode'): every admission grouping, the
        chunk-looped long prefill, and the page-aligned duplicate that
        forces the copy-on-write fork."""
        tok = 0

        def warm(w, count):
            nonlocal tok
            for _ in range(count):
                tok += 1
                srv.submit(np.full((w,), tok, np.int32), max_new_tokens=2)
            srv.run_until_drained()

        w = 16
        while w <= ps:
            for count in range(1, min(slots, max(1, budget // w)) + 1):
                warm(w, count)
            w *= 2
        warm(3 * ps + 16, 1)          # longer than any timed prompt
        dup = np.full((2 * ps,), cfg.vocab_size - 3, np.int32)
        for _ in range(2):
            srv.submit(dup, max_new_tokens=2)
            srv.run_until_drained()

    def warm_prefill(srv):
        """Same width sweep on a prefill-role replica: it can never
        finish a request (no decode), so each group prefills to the
        parked-handoff state and is then cancelled."""
        tok = 0
        w = 16
        while w <= ps:
            for count in range(1, min(slots, max(1, budget // w)) + 1):
                reqs = []
                for _ in range(count):
                    tok += 1
                    reqs.append(srv.submit(np.full((w,), tok, np.int32),
                                           max_new_tokens=2))
                for _ in range(40):
                    srv.step()
                    if all(r in srv.pending_handoffs() for r in reqs):
                        break
                for r in reqs:
                    srv.cancel(r.request_id)
            w *= 2
        r = srv.submit(np.full((3 * ps + 16,), 1, np.int32),
                       max_new_tokens=2)
        for _ in range(40):
            srv.step()
            if r in srv.pending_handoffs():
                break
        srv.cancel(r.request_id)

    def warm_fleet(router):
        """Transfers must run BEFORE end_warmup: the cross-pool
        transfer program only records its signature when a real adopt
        traces it through the attached watchdog. A repeated grouped
        prompt exercises the trie-hit adopt path too."""
        wprompts, wbudgets, wsessions = make_workload(3)
        reqs = []
        for p, b, s in zip(wprompts, wbudgets, wsessions):
            kw = {"session": s} if s is not None else {}
            reqs.append(router.submit(p, max_new_tokens=b, **kw))
        router.run_until_drained(max_steps=20_000)
        assert all(r.state.value == "finished" for r in reqs), \
            "disagg warmup did not drain"
        router.end_warmup()

    # -- arms (equal device count: two disjoint 4-device meshes) -------
    co_a = make_srv(devs[:4], "both")
    warm_admitting(co_a)
    co_b = make_srv(devs[4:], "both")
    warm_admitting(co_b)
    colocated = ReplicaRouter([co_a, co_b])
    warm_fleet(colocated)

    pre = make_srv(devs[:4], "prefill")
    warm_prefill(pre)
    dec = make_srv(devs[4:], "decode")
    warm_admitting(dec)
    disagg = ReplicaRouter([pre, dec])
    warm_fleet(disagg)

    servers = [co_a, co_b, pre, dec]
    if _SIGNATURES_PATH:
        extra = {"vocab_size": cfg.vocab_size,
                 "max_seed_len": 3 * ps + 16}
        for srv in servers:
            srv.export_signatures(_SIGNATURES_PATH, merge=True, extra=extra)

    def run_arm(router):
        for i in router.alive_replicas:
            rep = router.replicas[i]
            rep.metrics = ServingMetrics(None, registry=rep.registry,
                                         step_fn=lambda s=rep: s.step_id)
            # overhead_pct measures the TIMED phase only: drop the
            # warmup's instrumentation time and step wall
            rep.reset_efficiency_window()
        reqs, i, step = [], 0, 0
        t0 = time.perf_counter()
        while i < n_req or router.has_work():
            while i < n_req and arrivals[i] <= step:
                kw = {"session": sessions[i]} if sessions[i] else {}
                reqs.append(router.submit(prompts[i],
                                          max_new_tokens=budgets[i], **kw))
                i += 1
            router.step()
            step += 1
            if step > 50_000:
                break
        wall = time.perf_counter() - t0
        gaps = []
        for j in router.decode_capable:
            gaps += [g * 1e3
                     for g in router.replicas[j].metrics.step_gaps]
        arr = np.asarray(gaps) if gaps else np.zeros((1,))
        return {"wall_s": wall,
                "decode_gap_p50_ms": float(np.percentile(arr, 50)),
                "decode_gap_p99_ms": float(np.percentile(arr, 99)),
                "tokens": int(sum(len(r.output_tokens) for r in reqs)),
                "outputs": [list(r.output_tokens) for r in reqs]}

    # interleaved replications, per-metric medians (same discipline as
    # every serving row: single-CPU replays jitter enough to flip a
    # close verdict)
    reps = 3
    co_runs, dis_runs = [], []
    for _ in range(reps):
        co_runs.append(run_arm(colocated))
        dis_runs.append(run_arm(disagg))

    def _med(runs, key):
        return float(np.median([r[key] for r in runs]))

    parity = all(r["outputs"] == co_runs[0]["outputs"]
                 for r in co_runs + dis_runs)
    co_p99 = _med(co_runs, "decode_gap_p99_ms")
    dis_p99 = _med(dis_runs, "decode_gap_p99_ms")

    recompiles = colocated.recompiles + disagg.recompiles
    leaks = sum(s.pool.num_slots - s.pool.free_count - s.live_count
                for s in servers)
    invariants_ok = True
    try:
        colocated.check_invariants()
        disagg.check_invariants()
    except Exception:
        invariants_ok = False
    open_tl = [rid for s in servers for rid in s.timelines.open_ids()]
    timelines_complete = not open_tl

    dstats = disagg.stats()
    transferred_pages = max(
        1, dstats["transfer_bytes"] // dec.pool.page_nbytes)
    saved = dstats["transfer_pages_saved"]

    # fleet observability detail (the --require-complete-journeys /
    # --max-overhead-pct gates read these): journey completeness over
    # the whole disaggregated run, merged transfer-latency tail, and
    # fleet goodput + instrumentation overhead from FleetTelemetry
    journeys = disagg.journey_summary()
    fleet_eff = disagg.fleet.efficiency_snapshot()

    def arm_detail(runs):
        return {"decode_gap_p50_ms": round(_med(runs,
                                                "decode_gap_p50_ms"), 2),
                "decode_gap_p99_ms": round(_med(runs,
                                                "decode_gap_p99_ms"), 2),
                "wall_s": round(_med(runs, "wall_s"), 3),
                "tokens": runs[-1]["tokens"]}

    _emit({
        "metric": f"disaggregated prefill/decode (1P+1D page-transfer "
                  f"fleet vs colocated DP=2 at equal device count; "
                  f"{n_req} req Poisson, prompts {ps + 1}-{3 * ps - 1}, "
                  f"budgets 4-8, {num_pages} pages x {ps}): decode "
                  f"step-gap p99",
        "value": round(dis_p99, 2),
        "unit": "ms (lower is better)",
        "vs_baseline": round(co_p99 / max(dis_p99, 1e-9), 3),
        "detail": {
            "baseline": "colocated DP=2 router (two role-'both' paged "
                        "replicas on the same two disjoint 4-device "
                        "meshes, same workload/arrivals/sessions): every "
                        "admission chunk runs inside a step that live "
                        "decodes wait through. vs_baseline is its decode "
                        "step-gap p99 over the disaggregated arm's (>1: "
                        "the decode tail shrank)",
            "greedy_parity": bool(parity),
            "recompiles_after_warmup": int(recompiles),
            "slot_leaks": int(leaks),
            "invariants_ok": bool(invariants_ok),
            "timelines_complete": bool(timelines_complete),
            "replications": reps,
            "transfers": dstats["transfers"],
            "transfer_bytes": dstats["transfer_bytes"],
            "transfer_latency_p99_ms": round(
                disagg.transfer_latency.quantile(0.99), 3),
            "journeys": journeys,
            "efficiency": {
                "goodput_slo": round(fleet_eff["goodput_slo"], 4),
                "overhead_pct": round(
                    fleet_eff.get("overhead_pct", 0.0), 3),
            },
            "prefix": {
                "prefix_routed_handoffs": dstats["prefix_routed"],
                "transfer_pages_saved": int(saved),
                "transfer_page_hit_rate": round(
                    saved / (saved + transferred_pages), 4),
            },
            "colocated": arm_detail(co_runs),
            "disaggregated": arm_detail(dis_runs),
        },
    })


def serving_decode_main():
    """Raw-decode-speed row: the fused paged-attention decode kernel plus
    overlapped host scheduling (``paged_kv={"kernel": "on"}, overlap=True``)
    vs the dense gather/scatter oracle with serial stepping
    (``kernel="off", overlap=False``) — SAME engine, pool geometry and
    decode-heavy workload; greedy outputs must be bitwise identical
    across arms and replications (the kernel is a bitwise-parity
    reimplementation, not an approximation). Headline ``value`` is the
    kernel+overlap arm's p99 inter-token step gap; ``vs_baseline`` is
    the dense-serial p99 over it (>1: the streaming tail shrank).
    ``detail.efficiency.mfu`` rides the cost model for the
    ``check_regression.py --warn-metric`` floor, and the row carries the
    full zero-recompile stack: post-warmup watchdog count for
    ``--max-recompiles 0`` plus the ``--signatures`` warmup manifest for
    ``--require-signature-match``."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.serving.metrics import ServingMetrics

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation (the kernel
        # runs in Pallas interpret mode off-TPU, so parity and all the
        # static/recompile gates are exercised; only the speedup isn't)
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        n_req, slots, ps = 24, 4, 32
        len_lo, len_hi, gen_lo, gen_hi = 8, 24, 32, 64
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots, ps = 48, 8, 64
        len_lo, len_hi, gen_lo, gen_hi = 32, 128, 64, 192
    num_pages = slots * cfg.max_seq_len // ps

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    # decode-heavy closed loop: short prompts (single-chunk prefill),
    # long budgets — the steady state is all slots decoding, which is
    # exactly where the fused kernel and the deferred-fetch/overlap
    # pipeline pay off; prompt tokens start at 1 so the page-aligned
    # CoW warm prompt below (token 0) can never prefix-hit the workload
    prompts = [gen.integers(1, cfg.vocab_size,
                            size=int(gen.integers(len_lo, len_hi + 1)))
               .astype(np.int32) for _ in range(n_req)]
    budgets = [int(gen.integers(gen_lo, gen_hi + 1)) for _ in range(n_req)]

    def make_srv(kernel: bool) -> ServingEngine:
        # the measured arm carries the cost model (MFU) + generous SLO
        # targets (this row gates that goodput is MEASURED, not that a
        # CPU box meets a production SLO)
        return ServingEngine(
            engine, num_slots=slots, max_queue_depth=2 * n_req,
            prefill_chunk=ps, overlap=kernel, cost_model=kernel,
            slo={"ttft_ms": 120_000.0, "gap_ms": 2_000.0,
                 "window_steps": 64} if kernel else None,
            paged_kv={"page_size": ps, "num_pages": num_pages,
                      "kernel": "on" if kernel else "off"})

    def warm_arm(srv: ServingEngine) -> None:
        """Compile (and — as important — RECORD into the watchdog's
        warmup manifest) every program the timed run and the signature
        gate can reach. The ``__init__`` pre-warm runs before the
        watchdog attaches, so this sweep is what actually records each
        admission grouping: every singleton width bucket up to the
        chunk (``_jit_cur_scatter`` at ``int32[1]``), every
        (rows x width) group the prefill token budget allows (each
        power-of-two group width), the chunk-looped long prefill,
        decode and sampling. A page-aligned prompt submitted twice
        forces one full prefix hit + copy-on-write fork so the CoW
        program lands in the manifest too — graftcheck enumerates it
        for every paged config, hit or no hit."""
        tok = 0

        def warm(w: int, count: int) -> None:
            nonlocal tok
            for _ in range(count):
                tok += 1
                srv.submit(np.full((w,), tok % (cfg.vocab_size - 1) + 1,
                                   np.int32), max_new_tokens=2)
            srv.run_until_drained()

        budget = 2 * ps   # the ServingEngine default this row runs with
        w = 16
        while w <= ps:
            for count in range(1, min(slots, max(1, budget // w)) + 1):
                warm(w, count)
            w *= 2
        warm(4 * ps, 1)   # long prefill: drives the chunk loop
        for _ in range(2):  # 2nd pass full-hits page-aligned prefix -> CoW
            srv.submit(np.zeros((2 * ps,), np.int32), max_new_tokens=2)
            srv.run_until_drained()

    def run_arm(srv: ServingEngine, timed: bool) -> dict:
        if timed:  # fresh aggregates; warmup polluted them
            srv.metrics = ServingMetrics(None, registry=srv.registry,
                                         step_fn=lambda s=srv: s.step_id)
            srv.reset_efficiency_window()
        reqs = [srv.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        t0 = time.perf_counter()
        srv.run_until_drained(max_steps=50_000)
        wall = time.perf_counter() - t0
        s = srv.stats()
        s["wall_s"] = wall
        s["outputs"] = [list(r.output_tokens) for r in reqs]
        return s

    arm_kernel = make_srv(kernel=True)
    arm_dense = make_srv(kernel=False)
    assert arm_kernel.pool.kernel_active and not arm_dense.pool.kernel_active
    warm_arm(arm_kernel)
    warm_arm(arm_dense)
    # both arms fully warmed: the runtime watchdogs now count any cache
    # growth as a real recompile (both watch the SHARED engine jits, so
    # max() rather than sum() avoids double-counting those)
    arm_kernel.end_warmup()
    arm_dense.end_warmup()
    if _SIGNATURES_PATH:
        extra = {"vocab_size": cfg.vocab_size, "max_prompt_len": 4 * ps}
        arm_kernel.export_signatures(_SIGNATURES_PATH, merge=True,
                                     extra=extra)
        arm_dense.export_signatures(_SIGNATURES_PATH, merge=True,
                                    extra=extra)

    # interleaved replications with per-metric medians: single CPU
    # replays jitter ~10% run-to-run, enough to flip a close verdict
    reps = 3
    kernel_runs, dense_runs = [], []
    for _ in range(reps):
        kernel_runs.append(run_arm(arm_kernel, timed=True))
        dense_runs.append(run_arm(arm_dense, timed=True))
    # efficiency rollup for the LAST kernel replication (the window
    # resets per rep); must precede the traced replay, which resets again
    eff = arm_kernel.efficiency_snapshot()

    recompiles = max(arm_kernel.watchdog.recompiles,
                     arm_dense.watchdog.recompiles)
    # greedy: outputs must be bitwise identical across arms AND reps —
    # the kernel arm is a different executable and a different step
    # pipeline, but NOT a different function
    parity = all(r["outputs"] == dense_runs[0]["outputs"]
                 for r in kernel_runs + dense_runs)

    tracer_detail = None
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        arm_kernel.set_tracer(Tracer())
        run_arm(arm_kernel, timed=True)  # traced replay on the warmed arm
        n_events = arm_kernel.tracer.export(_TRACE_PATH)
        tracer_detail = {"path": _TRACE_PATH, "events": n_events}

    _MED_KEYS = ("tokens_per_s", "per_token_p50_ms", "per_token_p99_ms",
                 "step_gap_p50_ms", "step_gap_p99_ms", "ttft_p50_ms",
                 "ttft_p99_ms", "wall_s")

    def _median(runs):
        out = dict(runs[-1])
        for k in _MED_KEYS:
            out[k] = float(np.median([r[k] for r in runs]))
        return out

    kern, dense = _median(kernel_runs), _median(dense_runs)

    def arm_detail(s):
        return {"tokens_per_s": round(s["tokens_per_s"], 1),
                "step_gap_p50_ms": round(s["step_gap_p50_ms"], 2),
                "step_gap_p99_ms": round(s["step_gap_p99_ms"], 2),
                "per_token_p50_ms": round(s["per_token_p50_ms"], 2),
                "per_token_p99_ms": round(s["per_token_p99_ms"], 2),
                "ttft_p50_ms": round(s["ttft_p50_ms"], 1),
                "decode_steps": s["decode_steps"],
                "completed": s["completed"],
                "wall_s": round(s["wall_s"], 3)}

    _emit({
        "metric": f"fused paged-attention decode kernel + overlapped "
                  f"host scheduling ({n_req} req, {slots} slots, "
                  f"{num_pages} pages x {ps}, prompts {len_lo}-{len_hi}, "
                  f"budgets {gen_lo}-{gen_hi}): p99 inter-token gap",
        "value": round(kern["step_gap_p99_ms"], 2),
        "unit": "ms (lower is better)",
        "vs_baseline": round(dense["step_gap_p99_ms"] /
                             max(kern["step_gap_p99_ms"], 1e-9), 3),
        "detail": {
            "baseline": "dense gather/scatter decode (kernel='off') with "
                        "serial stepping (overlap=False) on the same "
                        "engine, pool geometry and workload — the bitwise "
                        "oracle the kernel must match. vs_baseline is the "
                        "dense arm's p99 inter-token gap over the kernel "
                        "arm's (>1: the tail shrank)",
            "greedy_parity": bool(parity),
            "recompiles_after_warmup": int(recompiles),
            "kernel_backend": "pallas" if not on_cpu else
                              "pallas-interpret (CPU validation)",
            "tracer": tracer_detail,
            "replications": reps,
            "tokens_per_s_ratio": round(kern["tokens_per_s"] /
                                        max(dense["tokens_per_s"], 1e-9),
                                        3),
            "efficiency": {
                "mfu": round(eff.get("mfu") or 0.0, 6),
                "bandwidth_util": round(
                    eff.get("bandwidth_util") or 0.0, 6),
                "hbm_peak_bytes": eff.get("hbm_peak_bytes"),
                "hbm_drift": eff.get("hbm_drift"),
                "goodput_slo": round(eff.get("goodput_slo") or 0.0, 4),
                "slo_gap_p99_ms": round(eff.get("gap_p99_ms") or 0.0, 2),
                "overhead_pct": round(eff.get("overhead_pct") or 0.0, 3),
                "cost_model_unavailable":
                    eff["costs"]["unavailable"] if "costs" in eff else None,
            },
            "paged_kernel": arm_detail(kern),
            "dense_oracle": arm_detail(dense),
        },
    })


def serving_chaos_main():
    """Fault-tolerant serving row: the SAME workload driven through a
    fault-free arm and a chaos arm with a deterministic fault schedule
    (admit-OOM, NaN logits, mid-step host exception, slow dispatch) on
    a server running every resilience feature — numerics guard,
    degradation ladder, automatic pressure preemption. The row reports
    goodput retained under faults and gates on the invariants a fault
    may never break: zero slot leaks, clean engine bookkeeping
    (``check_invariants``), complete request timelines (every request
    terminal with a reason), zero post-warmup recompiles."""
    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine
    from deepspeed_tpu.serving.metrics import ServingMetrics
    from deepspeed_tpu.serving.resilience import FaultInjector, InjectedFault

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        n_req, slots = 24, 4
        len_lo, len_hi, gen_lo, gen_hi = 16, 48, 8, 24
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        n_req, slots = 32, 8
        len_lo, len_hi, gen_lo, gen_hi = 32, 128, 16, 64

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    gen = np.random.default_rng(0)
    prompts = [gen.integers(0, cfg.vocab_size,
                            size=int(gen.integers(len_lo, len_hi + 1)))
               .astype(np.int32) for _ in range(n_req)]
    budgets = [int(gen.integers(gen_lo, gen_hi + 1)) for _ in range(n_req)]

    # the measured fault plan, pinned to call ordinals so every rerun
    # injects the identical failures at the identical points. Spec decode
    # stays OFF in this row (the NaN point lives in the plain decode
    # path); drafter faults are covered by the chaos unit suite.
    fault_plan = {"admit_oom": [3], "nan_logits": [5],
                  "step_host_error": [9], "slow_dispatch": [2, 12]}
    # degradation thresholds low enough that the all-at-once submission
    # walks HEALTHY -> OVERLOADED and back while the queue drains
    degr = {"queue_pressured": max(slots, 4),
            "queue_overloaded": max(2 * slots, 10), "cooldown_steps": 4}

    def make_srv(faulty: bool) -> ServingEngine:
        return ServingEngine(
            engine, num_slots=slots, max_queue_depth=2 * n_req,
            guard_numerics=True, degradation=dict(degr),
            preempt_queue_threshold=n_req // 2, step_wall_budget_ms=250.0,
            fault_injector=FaultInjector(seed=0) if faulty else None)

    def warm(srv: ServingEngine) -> None:
        """Compile every (batch-bucket x width-bucket) admission program
        a preemption-resume can reach (resumed seeds land on LARGER
        width buckets than their prompts), plus chunked prefill, decode,
        the numerics guard and sampling — all before the measured run,
        so the zero-recompile gate is meaningful."""
        w = 16
        while w <= srv.pool.capacity:
            for count in range(1, slots + 1):
                for _ in range(count):
                    srv.submit(np.ones((min(w, srv.pool.capacity - 2),),
                                       np.int32), max_new_tokens=2)
                srv.run_until_drained()
            w *= 2
        srv.submit(np.ones((srv.pool.capacity - 2,), np.int32),
                   max_new_tokens=2)
        srv.run_until_drained()

    def run_arm(srv: ServingEngine, plan=None) -> dict:
        srv.metrics = ServingMetrics(None, registry=srv.registry,
                                     step_fn=lambda s=srv: s.step_id)
        if srv.faults is not None:
            srv.faults.load_schedule(plan or {})
        reqs = [srv.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        injected_aborts = 0
        t0 = time.perf_counter()
        guard = 0
        while srv.pending or srv.live_count:
            try:
                srv.step()
            except InjectedFault:
                # the harness absorbs INJECTED failures (a real serving
                # front-end would log and carry on); anything else is a
                # genuine bug and propagates
                injected_aborts += 1
            guard += 1
            assert guard < 10_000, "chaos drain did not terminate"
        wall = time.perf_counter() - t0
        s = srv.stats()
        s["wall_s"] = wall
        s["injected_aborts"] = injected_aborts
        s["reqs"] = reqs
        return s

    srv_chaos = make_srv(faulty=True)
    srv_clean = make_srv(faulty=False)
    warm(srv_chaos)   # empty schedule: warmup consumes no fault ordinals
    warm(srv_clean)
    srv_chaos.end_warmup()
    srv_clean.end_warmup()

    clean = run_arm(srv_clean)
    chaos = run_arm(srv_chaos, plan=fault_plan)

    # -- the gates ------------------------------------------------------
    leaks = slots - srv_chaos.pool.free_count - srv_chaos.live_count
    invariants_ok = True
    try:
        srv_chaos.check_invariants()
        srv_clean.check_invariants()
    except Exception:
        invariants_ok = False
    open_tl = srv_chaos.timelines.open_ids()
    terminal_ok = all(
        r.state.value in ("finished", "rejected", "failed")
        and (r.finish_reason is not None or r.reject_reason is not None)
        for r in chaos["reqs"])
    recompiles = max(srv_chaos.watchdog.recompiles,
                     srv_clean.watchdog.recompiles)
    goodput = chaos["completed"] / max(clean["completed"], 1)
    # snapshot before the traced replay below re-fires the schedule
    faults_fired = dict(srv_chaos.faults.summary()["fired"])

    # -- flight-recorder post-mortem drill ------------------------------
    # a FRESH server (same warmed engine) with an armed state_corruption
    # point and a dump_dir: the planted corruption breaks slot
    # bookkeeping at the first step's tail, the check_invariants audit
    # raises, and EXACTLY ONE self-contained post-mortem JSON must land
    # under --dump-dir (a tmpdir when the flag is absent)
    import glob
    import os
    import tempfile

    dump_dir = _DUMP_DIR or tempfile.mkdtemp(prefix="dstpu-postmortem-")
    srv_pm = make_srv(faulty=True)
    srv_pm.dump_dir = dump_dir
    srv_pm.recorder.dump_dir = dump_dir
    srv_pm.faults.load_schedule({"state_corruption": [1]})
    for p, b in zip(prompts[:slots], budgets[:slots]):
        srv_pm.submit(p, max_new_tokens=b)
    srv_pm.step()           # corruption fires at this step's tail
    violation = None
    try:
        srv_pm.check_invariants()
    except Exception as e:  # InvariantViolation; dumping rides the raise
        violation = type(e).__name__
    pm_files = sorted(os.path.basename(f) for f in glob.glob(
        os.path.join(dump_dir, "postmortem-*.json")))
    post_mortem = {"dir": dump_dir, "files": pm_files,
                   "raised": violation,
                   "exactly_one": len(pm_files) == 1}

    tracer_detail = None
    if _TRACE_PATH:
        from deepspeed_tpu.telemetry import Tracer

        srv_chaos.set_tracer(Tracer())
        run_arm(srv_chaos, plan=fault_plan)  # traced replay, same faults
        tracer_detail = {"path": _TRACE_PATH,
                         "events": srv_chaos.tracer.export(_TRACE_PATH)}

    _emit({
        "metric": f"fault-tolerant serving under deterministic chaos "
                  f"({n_req} req, {slots} slots, faults: "
                  f"{sorted(k for k, v in fault_plan.items() if v)}): "
                  f"goodput retained vs fault-free arm",
        "value": round(goodput, 3),
        "unit": "fraction of fault-free completions (higher is better)",
        "vs_baseline": round(goodput, 3),
        "detail": {
            "baseline": "identical engine/config/workload with no fault "
                        "injector; goodput = chaos completions over "
                        "fault-free completions (lost requests are the "
                        "ones a fault FAILED — never a leaked slot or a "
                        "stranded queue entry)",
            "slot_leaks": int(leaks),
            "invariants_ok": bool(invariants_ok),
            "timelines_complete": bool(not open_tl and terminal_ok),
            "recompiles_after_warmup": int(recompiles),
            "tracer": tracer_detail,
            "fault_plan": {k: list(v) for k, v in fault_plan.items()},
            "faults_fired": faults_fired,
            "injected_aborts": chaos["injected_aborts"],
            "post_mortem": post_mortem,
            "chaos": {
                "completed": chaos["completed"],
                "failed": chaos["failed"],
                "failed_reasons": chaos["failed_reasons"],
                "preempted": chaos["preempted"],
                "step_overruns": chaos["step_overruns"],
                "load_transitions": chaos["load_transitions"],
                "tokens_per_s": round(chaos["new_tokens"] /
                                      chaos["wall_s"], 1),
            },
            "fault_free": {
                "completed": clean["completed"],
                "failed": clean["failed"],
                "preempted": clean["preempted"],
                "load_transitions": clean["load_transitions"],
                "tokens_per_s": round(clean["new_tokens"] /
                                      clean["wall_s"], 1),
            },
        },
    })


def serving_async_main():
    """Async front-end row: Poisson load at three priority tiers driven
    through the REAL HTTP/SSE server over a localhost socket. The
    standard tier's TTFT target is unmeetable by construction, so its
    burn-rate alert pages and the scheduler sheds the batch tier while
    the interactive tier keeps its goodput — that top-class goodput is
    the headline, measured only while the bottom class is actively
    shed. Gates: zero slot leaks, clean invariants, complete timelines
    (every SSE stream terminal), zero post-warmup recompiles."""
    import asyncio

    import jax
    import jax.numpy as jnp

    _enable_persistent_cache()

    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer_lm import (TransformerConfig,
                                                     TransformerLM)
    from deepspeed_tpu.serving import ServingEngine, ServingFrontend
    from deepspeed_tpu.serving.metrics import ServingMetrics

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:  # keep the row runnable for local validation
        cfg = TransformerConfig(vocab_size=512, max_seq_len=256, n_embd=64,
                                n_layer=2, n_head=4, dtype=jnp.float32)
        slots = 4
        n_int, n_std, n_batch = 12, 10, 10
    else:
        cfg = TransformerConfig(vocab_size=50257, max_seq_len=1024,
                                n_embd=768, n_layer=12, n_head=12,
                                dtype=jnp.bfloat16)
        slots = 8
        n_int, n_std, n_batch = 16, 12, 12

    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                        method=model.logits)["params"]
    engine = ds.init_inference(model, model_parameters=params,
                               dtype="fp32" if on_cpu else "bf16", mp_size=1)

    # the standard tier's contract is unmeetable ON PURPOSE: every
    # finish blows TTFT, burn = (1-0)/(1-0.95) = 20 >= page_burn on
    # both horizons, and the shed floor drops to rank(standard) — so
    # batch (ranked below) is shed while interactive/standard admit.
    lenient = {"ttft_ms": 6e5, "gap_ms": 6e5}
    slo_cfg = {
        **lenient,                      # default class: lenient
        "window_steps": 8, "windows": 4,
        "goodput_target": 0.95, "warn_burn": 2.0, "page_burn": 10.0,
        "classes": {
            "interactive": dict(lenient),
            "standard": {"ttft_ms": 1e-3, "gap_ms": None},
            "batch": dict(lenient),
        },
    }
    srv = ServingEngine(engine, num_slots=slots, max_queue_depth=64,
                        priority=True, slo=slo_cfg)

    def warm() -> None:
        """Compile every admission/decode program the measured run (and
        a burn-preemption resume) can reach before end_warmup(), so the
        zero-recompile gate is meaningful."""
        w = 16
        while w <= min(srv.pool.capacity, 64):
            for count in range(1, slots + 1):
                for _ in range(count):
                    srv.submit(np.ones((min(w, srv.pool.capacity - 2),),
                                       np.int32), max_new_tokens=2)
                srv.run_until_drained()
            w *= 2

    warm()
    srv.end_warmup()
    # measured run starts from clean counters: fresh request metrics,
    # zeroed SLO windows/alerts and cost-model totals
    srv.metrics = ServingMetrics(None, registry=srv.registry,
                                 step_fn=lambda s=srv: s.step_id)
    srv.reset_efficiency_window()

    # deterministic workload: prompts, budgets and Poisson gaps are all
    # drawn up front (async interleaving must not reorder rng draws)
    gen = np.random.default_rng(0)

    def _tier(n, mean_gap_s):
        return [{"prompt": gen.integers(1, cfg.vocab_size,
                                        size=int(gen.integers(8, 25)))
                 .astype(int).tolist(),
                 "max_new_tokens": int(gen.integers(8, 17)),
                 "gap_s": float(gen.exponential(mean_gap_s))}
                for _ in range(n)]

    tiers = {"interactive": _tier(n_int, 0.02),
             "standard": _tier(n_std, 0.02),
             "batch": _tier(n_batch, 0.015)}
    burn_seed = _tier(4, 0.0)           # phase 1: ignite the standard burn

    # -- minimal stdlib HTTP/SSE client (mirrors the server's framing) --
    def _http_bytes(method, path, body=None):
        payload = b"" if body is None else json.dumps(body).encode()
        return (f"{method} {path} HTTP/1.1\r\nHost: b\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n"
                .encode("latin-1") + payload)

    async def _next_frame(reader):
        try:
            block = await reader.readuntil(b"\n\n")
        except asyncio.IncompleteReadError:
            return None
        event, data = None, None
        for line in block.decode().strip().split("\n"):
            if line.startswith("event: "):
                event = line[len("event: "):]
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        return event, data

    async def _generate(port, cls, spec):
        """One POST /v1/generate exchange; returns a result record."""
        rec = {"cls": cls, "status": None, "reject_reason": None,
               "ttft_ms": None, "tokens": 0, "terminal": None}
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        t0 = time.perf_counter()
        writer.write(_http_bytes("POST", "/v1/generate", {
            "prompt": spec["prompt"],
            "max_new_tokens": spec["max_new_tokens"],
            "priority": cls, "tenant": cls}))
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        rec["status"] = int(head.decode("latin-1").split(" ")[1])
        if rec["status"] != 200:
            body = await reader.read()
            info = json.loads(body) if body else {}
            rec["reject_reason"] = info.get("reject_reason")
        else:
            while True:
                fr = await _next_frame(reader)
                if fr is None:
                    break
                ev, _ = fr
                if ev == "token":
                    if rec["tokens"] == 0:
                        rec["ttft_ms"] = (time.perf_counter() - t0) * 1e3
                    rec["tokens"] += 1
                elif ev in ("done", "error"):
                    rec["terminal"] = ev
                    break
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass
        return rec

    async def _healthz(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(_http_bytes("GET", "/healthz"))
        await writer.drain()
        raw = await reader.read()
        writer.close()
        await writer.wait_closed()
        return json.loads(raw.partition(b"\r\n\r\n")[2])

    async def drive():
        fe = ServingFrontend(srv, port=0, idle_poll_s=0.002)
        await fe.start()
        port = fe.port
        results, alerts_at_batch = [], {}
        try:
            # phase 1: burn the standard tier, wait for the page alert
            results += await asyncio.gather(*[
                _generate(port, "standard", s) for s in burn_seed])
            for _ in range(300):
                alerts_at_batch = (await _healthz(port))["class_alerts"]
                if alerts_at_batch.get("standard") == "page":
                    break
                await asyncio.sleep(0.01)

            # phase 2: Poisson arrivals at all three tiers while the
            # burn is hot — batch lands on the shed floor
            async def tier(cls):
                tasks = []
                for spec in tiers[cls]:
                    await asyncio.sleep(spec["gap_s"])
                    tasks.append(asyncio.create_task(
                        _generate(port, cls, spec)))
                return await asyncio.gather(*tasks)

            for part in await asyncio.gather(*(tier(c) for c in tiers)):
                results += part
        finally:
            await fe.stop()
        return results, alerts_at_batch

    t0 = time.perf_counter()
    results, alerts = asyncio.run(asyncio.wait_for(drive(), timeout=600))
    wall = time.perf_counter() - t0

    # -- per-class client-side rollup -----------------------------------
    def _client(cls):
        rs = [r for r in results if r["cls"] == cls]
        ttfts = [r["ttft_ms"] for r in rs if r["ttft_ms"] is not None]
        return {
            "sent": len(rs),
            "streamed": sum(1 for r in rs if r["status"] == 200),
            "shed": sum(1 for r in rs if r["status"] == 429
                        and r["reject_reason"] == "retry_after"),
            "rejected_other": sum(1 for r in rs if r["status"] not in
                                  (200, None) and r["status"] != 429),
            "ttft_p50_ms": (round(float(np.percentile(ttfts, 50)), 1)
                            if ttfts else None),
            "ttft_p99_ms": (round(float(np.percentile(ttfts, 99)), 1)
                            if ttfts else None),
        }

    client = {cls: _client(cls) for cls in
              ("interactive", "standard", "batch")}

    # -- the gates ------------------------------------------------------
    leaks = slots - srv.pool.free_count - srv.live_count
    invariants_ok = True
    try:
        srv.check_invariants()
    except Exception:
        invariants_ok = False
    # timelines complete on BOTH sides of the socket: no open engine
    # timelines, and every accepted SSE stream reached a terminal frame
    open_tl = srv.timelines.open_ids()
    terminal_ok = all(r["terminal"] == "done"
                      for r in results if r["status"] == 200)
    recompiles = srv.watchdog.recompiles

    snap = srv.slo.snapshot()
    pc = snap["per_class"]
    top = pc.get("interactive", {"admitted": 0, "good": 0})
    top_goodput = (top["good"] / top["admitted"]
                   if top["admitted"] else 1.0)
    eff = srv.efficiency_snapshot()
    # --min-goodput gates the TOP class: the row's claim is that the
    # paying tier keeps its SLO while a lower tier is being shed
    eff["goodput_slo_overall"] = eff.get("goodput_slo")
    eff["goodput_slo"] = top_goodput
    stats = srv.stats()

    _emit({
        "metric": f"async HTTP/SSE serving, 3 priority tiers under "
                  f"burn-driven shedding ({slots} slots, "
                  f"{len(results)} requests): interactive goodput "
                  f"while batch is shed",
        "value": round(top_goodput, 3),
        "unit": "fraction of interactive admissions finishing within "
                "SLO (higher is better)",
        "vs_baseline": round(top_goodput, 3),
        "detail": {
            "baseline": "the standard tier's TTFT contract is "
                        "unmeetable by construction, paging its burn "
                        "alert; goodput_slo is the INTERACTIVE class "
                        "(good/admitted from the SLO tracker) measured "
                        "while batch submissions are shed with 429 + "
                        "Retry-After over the real localhost socket",
            "slot_leaks": int(leaks),
            "invariants_ok": bool(invariants_ok),
            "timelines_complete": bool(not open_tl and terminal_ok),
            "recompiles_after_warmup": int(recompiles),
            "efficiency": eff,
            "class_alerts": snap and {
                k: v["alert"] for k, v in pc.items()},
            "alerts_when_batch_arrived": alerts,
            "batch_actively_shed": client["batch"]["shed"] > 0,
            "per_class_slo": pc,
            "per_class_http": client,
            "engine": {
                "completed": stats["completed"],
                "rejected": stats["rejected"],
                "preempted": stats["preempted"],
                "cancelled": stats["cancelled"],
                "new_tokens": stats["new_tokens"],
            },
            "wall_s": round(wall, 2),
            "requests_per_s": round(len(results) / wall, 2),
        },
    })


if __name__ == "__main__":
    import sys

    argv = sys.argv[1:]
    if "--json" in argv:
        _JSON_PATH = argv[argv.index("--json") + 1]
    if "--trace" in argv:
        _TRACE_PATH = argv[argv.index("--trace") + 1]
    if "--dump-dir" in argv:
        _DUMP_DIR = argv[argv.index("--dump-dir") + 1]
    if "--signatures" in argv:
        _SIGNATURES_PATH = argv[argv.index("--signatures") + 1]
    if "serving-chaos" in argv:
        entry = serving_chaos_main
    elif "serving-async" in argv:
        entry = serving_async_main
    elif "serving-tp" in argv:
        entry = serving_tp_main
    elif "serving-disagg" in argv:
        entry = serving_disagg_main
    elif "paging" in argv:
        entry = paging_main
    elif "serving-decode" in argv:
        entry = serving_decode_main
    elif "serving-stall" in argv:
        entry = serving_stall_main
    elif "spec" in argv:
        entry = spec_main
    elif "serving" in argv:
        entry = serving_main
    else:
        entry = main
    # the tunneled backend's remote-compile service intermittently 500s
    # (observed r3: "tpu_compile_helper subprocess exit code 1" for ~hours);
    # retry with backoff so a transient outage doesn't zero the round
    attempts = 6
    for attempt in range(attempts):
        try:
            entry()
            break
        except Exception as e:  # noqa: BLE001
            if attempt == attempts - 1:
                raise
            import sys
            delay = 120 * (attempt + 1)
            print(f"bench attempt {attempt + 1} failed ({e}); retrying "
                  f"in {delay}s", file=sys.stderr, flush=True)
            time.sleep(delay)
