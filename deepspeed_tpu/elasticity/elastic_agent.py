"""Elastic agent — restart-on-failure worker supervision.

Capability parity with reference ``deepspeed/elasticity/elastic_agent.py:28
DSElasticAgent`` (extends torch-elastic's LocalElasticAgent: master addr/port
via store, worker env assembly, monitor loop with max_restarts). TPU-native
equivalence: there is no torch-elastic rendezvous — the agent supervises the
local worker processes directly and restarts the (fixed-size) local group on
failure, exporting ``DS_ELASTIC_RESTART_COUNT`` so workers can detect the
restart generation. *Resizing* to a different world size is the launcher's
job (re-invoke with a new hostfile; ``compute_elastic_config`` gives the
compatible sizes) and training state rides the universal checkpoint.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from ..utils.logging import logger


class WorkerSpec:
    """What to run for each local worker (≅ torch-elastic WorkerSpec)."""

    def __init__(self, entrypoint: Sequence[str], local_world_size: int,
                 master_addr: str = "127.0.0.1", master_port: int = 29500,
                 max_restarts: int = 3, monitor_interval: float = 1.0,
                 node_rank: int = 0, nnodes: int = 1,
                 global_rank_offset: Optional[int] = None,
                 world_size: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None):
        self.entrypoint = list(entrypoint)
        self.local_world_size = local_world_size
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.node_rank = node_rank
        self.nnodes = nnodes
        # heterogeneous slots per node: the launcher passes the true offset /
        # world size; the homogeneous defaults only hold when every node has
        # local_world_size slots
        self.global_rank_offset = global_rank_offset \
            if global_rank_offset is not None else node_rank * local_world_size
        self.world_size = world_size \
            if world_size is not None else nnodes * local_world_size
        self.env = dict(env or {})


class DSElasticAgent:
    """Supervises local workers; restarts the whole local group on failure
    up to ``max_restarts`` times (torch-elastic semantics: any worker failure
    fails the group)."""

    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.restarts = 0
        self._procs: List[subprocess.Popen] = []

    def _worker_env(self, local_rank: int) -> Dict[str, str]:
        spec = self.spec
        env = dict(os.environ)
        env.update(spec.env)
        global_rank = spec.global_rank_offset + local_rank
        env.update({
            "LOCAL_RANK": str(local_rank),
            "RANK": str(global_rank),
            "LOCAL_SIZE": str(spec.local_world_size),
            "WORLD_SIZE": str(spec.world_size),
            "MASTER_ADDR": spec.master_addr,
            "MASTER_PORT": str(spec.master_port),
            # jax.distributed.initialize contract (same as launch.py)
            "JAX_COORDINATOR_ADDRESS":
                f"{spec.master_addr}:{spec.master_port}",
            "JAX_PROCESS_ID": str(global_rank),
            "JAX_NUM_PROCESSES": str(spec.world_size),
            # restart generation: lets workers detect a re-formed job
            "DS_ELASTIC_RESTART_COUNT": str(self.restarts),
        })
        return env

    def _start_workers(self) -> None:
        self._procs = []
        for local_rank in range(self.spec.local_world_size):
            p = subprocess.Popen(self.spec.entrypoint,
                                 env=self._worker_env(local_rank))
            self._procs.append(p)
        logger.info(f"elastic agent: started {len(self._procs)} workers "
                    f"(restart {self.restarts}/{self.spec.max_restarts})")

    def _kill_workers(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def _monitor(self) -> Optional[int]:
        """Returns the failing exit code, or None if all workers succeeded."""
        while True:
            codes = [p.poll() for p in self._procs]
            failed = [c for c in codes if c is not None and c != 0]
            if failed:
                return failed[0]
            if all(c == 0 for c in codes):
                return None
            time.sleep(self.spec.monitor_interval)

    def run(self) -> int:
        """Supervise until success or restarts exhausted; returns exit code."""
        self._start_workers()
        while True:
            code = self._monitor()
            if code is None:
                logger.info("elastic agent: all workers finished successfully")
                return 0
            self._kill_workers()
            if self.restarts >= self.spec.max_restarts:
                logger.error(
                    f"elastic agent: worker failed (exit {code}) and "
                    f"max_restarts={self.spec.max_restarts} exhausted")
                return code
            self.restarts += 1
            logger.warning(f"elastic agent: worker failed (exit {code}); "
                           f"restarting group "
                           f"({self.restarts}/{self.spec.max_restarts})")
            self._start_workers()

    def shutdown(self) -> None:
        self._kill_workers()
