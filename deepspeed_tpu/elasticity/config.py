"""Elasticity config + exceptions.

Capability parity with reference ``deepspeed/elasticity/config.py`` —
``ElasticityConfig`` holding the elastic-batch search space and the
exception taxonomy (ElasticityError / ElasticityConfigError /
ElasticityIncompatibleWorldSize).
"""

from __future__ import annotations

from typing import Any, Dict, List

LATEST_ELASTICITY_VERSION = 0.2
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"


class ElasticityError(Exception):
    """Base exception for all elasticity related errors."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the given elastic config."""


class ElasticityConfig:
    """Constructed from the ``elasticity`` JSON block:

    {
        "enabled": true,
        "max_train_batch_size": 2000,
        "micro_batch_sizes": [2, 4, 6],
        "min_gpus": 1,
        "max_gpus": 10000,
        "min_time": 20,
        "version": 0.2,
        "ignore_non_elastic_batch_info": false,
        "num_gpus_per_node": 1,
        "model_parallel_size": 1
    }

    Key names keep the reference spelling (``gpus``) so unmodified configs
    parse; on TPU a "gpu" is a chip.
    """

    def __init__(self, param_dict: Dict[str, Any]):
        self.enabled = bool(param_dict.get("enabled", False))
        if self.enabled:
            if "max_train_batch_size" not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing max_train_batch_size")
            if "micro_batch_sizes" not in param_dict:
                raise ElasticityConfigError(
                    "Elasticity config missing micro_batch_sizes")
        self.max_acceptable_batch_size = int(
            param_dict.get("max_train_batch_size", 0) or 0)
        self.micro_batches: List[int] = list(
            param_dict.get("micro_batch_sizes", []) or [])
        if self.enabled:
            if any(not isinstance(m, int) or m <= 0 for m in self.micro_batches):
                raise ElasticityConfigError(
                    f"micro_batch_sizes must be positive ints, got "
                    f"{self.micro_batches}")
            if self.max_acceptable_batch_size < max(self.micro_batches, default=0):
                raise ElasticityConfigError(
                    f"max_train_batch_size ({self.max_acceptable_batch_size}) "
                    f"must be >= every micro batch {self.micro_batches}")
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", 10000))
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = int(param_dict.get("min_time", 0))
        self.version = float(param_dict.get("version", 0.2))
        self.ignore_non_elastic_batch_info = bool(
            param_dict.get("ignore_non_elastic_batch_info", False))
        self.num_gpus_per_node = int(param_dict.get("num_gpus_per_node", 1))
        self.model_parallel_size = int(param_dict.get("model_parallel_size", 1))

    def repr_dict(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "max_train_batch_size": self.max_acceptable_batch_size,
            "micro_batch_sizes": self.micro_batches,
            "min_gpus": self.min_gpus,
            "max_gpus": self.max_gpus,
            "min_time": self.min_time,
            "version": self.version,
            "num_gpus_per_node": self.num_gpus_per_node,
            "model_parallel_size": self.model_parallel_size,
        }
