"""Elastic-batch math.

Capability parity with reference ``deepspeed/elasticity/elasticity.py`` —
``compute_elastic_config`` (:233) picks a total train batch size that is
compatible (via gradient accumulation) with as many device counts as
possible, so a job can be rescheduled across the allowed chip-count range
without changing convergence behavior. v0.1 (:83) searches highly-composite
scalings of the micro-batches; v0.2 (:126) works at node granularity with a
fixed current DP size and model parallelism.

The arithmetic is hardware-agnostic; on TPU "gpus" = chips and
"num_gpus_per_node" = chips per host. Re-meshing after a world-size change
is handled by the universal checkpoint (deepspeed_tpu/checkpoint/).
"""

from __future__ import annotations

import json
import math
import os
from functools import reduce
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger
from .config import (
    DEEPSPEED_ELASTICITY_CONFIG,
    LATEST_ELASTICITY_VERSION,
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)

# Highly composite numbers — maximally divisible scaling factors; enough to
# reach ~720k batch (reference elasticity.py:21 uses the same well-known
# integer sequence, OEIS A002182).
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720,
]


def _lcm(values: List[int]) -> int:
    return reduce(lambda a, b: a * b // math.gcd(a, b), values, 1)


def get_candidate_batch_sizes(base_list: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """Each base scaled by the largest HCN keeping base*hcn <= max."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
            continue
        limit = max_acceptable_batch_size // base
        scale = 1
        for hcn in HCN_LIST:
            if hcn > limit:
                break
            scale = hcn
        candidates.add(scale * base)
    out = sorted(candidates)
    logger.info(f"Candidate batch sizes: {out}")
    return out


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """All world sizes w in [min, max] such that batch_size = micro * gas * w
    for some micro in micro_batches and integer gas."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro != 0:
            continue
        max_gpus = batch_size // micro
        if min_valid_gpus <= max_gpus <= max_valid_gpus:
            valid.add(max_gpus)
        for w in range(1, max_gpus // 2 + 1):
            if w > max_valid_gpus:
                break
            if w >= min_valid_gpus and max_gpus % w == 0:
                valid.add(w)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes: List[int],
                        micro_batches: List[int], min_gpus: int, max_gpus: int,
                        prefer_larger: bool) -> Tuple[int, List[int]]:
    """Candidate with the most compatible world sizes (ties broken by
    batch-size preference)."""
    best_count = 0
    best_valid: Optional[List[int]] = None
    best_batch = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        valid = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        better_tie = (prefer_larger and batch_size > best_batch) or \
            (not prefer_larger and batch_size < best_batch)
        if len(valid) > best_count or (len(valid) == best_count and better_tie):
            best_count = len(valid)
            best_valid = valid
            best_batch = batch_size
    return best_batch, best_valid


def _get_compatible_gpus_v01(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             min_gpus: Optional[int] = None,
                             max_gpus: Optional[int] = None,
                             prefer_larger: bool = True):
    """v0.1: bases = each micro batch and their LCM; scale by HCNs; pick the
    batch compatible with the most world sizes in [min_gpus, max_gpus]."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            f"all micro batches {micro_batches} must be <= "
            f"max_acceptable_batch_size {max_acceptable_batch_size}")
    base_list = list(micro_batches) + [_lcm(micro_batches)]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus,
                               prefer_larger)


def _get_compatible_gpus_v02(micro_batches: List[int],
                             max_acceptable_batch_size: int,
                             current_num_gpus: int,
                             min_gpus: Optional[int] = None,
                             max_gpus: Optional[int] = None,
                             prefer_larger: bool = True,
                             num_gpus_per_node: int = 1,
                             model_parallel_size: int = 1):
    """v0.2: node-granular (world sizes are whole nodes), model-parallel
    aware (DP size = chips / mp). Falls back to scaling the current DP size
    when the v0.1 answer doesn't include it."""
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(
            f"num_gpus_per_node {num_gpus_per_node} must be divisible by "
            f"model_parallel_size {model_parallel_size}")

    def get_microbatch(final_batch_size: int) -> Optional[int]:
        candidate = None
        for micro in micro_batches:
            if (final_batch_size // current_num_gpus) % micro == 0:
                if candidate is None or (prefer_larger and micro > candidate):
                    candidate = micro
        return candidate

    dp_size_per_node = num_gpus_per_node // model_parallel_size
    final_batch_size, valid_nodes = _get_compatible_gpus_v01(
        micro_batches,
        int(max_acceptable_batch_size / dp_size_per_node),
        int((min_gpus or 1) / num_gpus_per_node) or 1,
        int((max_gpus or current_num_gpus) / num_gpus_per_node) or 1,
        prefer_larger=prefer_larger)
    final_batch_size = int(final_batch_size) * dp_size_per_node
    valid_dp_sizes = [n * dp_size_per_node for n in (valid_nodes or [])]
    if current_num_gpus // model_parallel_size in valid_dp_sizes:
        return final_batch_size, valid_dp_sizes, get_microbatch(final_batch_size)

    # fallback: keep the current DP size, choose the largest batch under max
    current_dp_size = (current_num_gpus // num_gpus_per_node) * dp_size_per_node
    candidates = []
    for micro in micro_batches:
        min_batch = micro * current_dp_size
        candidates.append(int(max_acceptable_batch_size // min_batch) * min_batch)
    batch = max(candidates) if prefer_larger else min(candidates)
    return batch, [int(current_dp_size)], get_microbatch(batch)


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """The resource scheduler and runtime must agree on the elastic search
    space (reference elasticity.py:208)."""
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        sched = ElasticityConfig(
            json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
        runtime = ElasticityConfig(runtime_elastic_config_dict)
        for field in ("max_acceptable_batch_size", "micro_batches", "version"):
            if getattr(runtime, field) != getattr(sched, field):
                raise ElasticityConfigError(
                    f"Elastic config '{field}={getattr(sched, field)}' seen by "
                    f"resource scheduler does not match runtime "
                    f"{field}={getattr(runtime, field)}")
    else:
        logger.warning(
            f"{DEEPSPEED_ELASTICITY_CONFIG} env var not found; cannot "
            "guarantee the resource scheduler will scale this job with "
            "compatible chip counts")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Compute (final_batch_size, valid_gpus[, micro_batch]) for an elastic
    job — reference elasticity.py:233. Deterministic for a given config, so
    the scheduler and every runtime agree.
    """
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"expected ds_config dict, got {type(ds_config)}: {ds_config}")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError(
            "'elasticity' is missing from the config json")
    elastic_config_dict = ds_config["elasticity"]
    if not elastic_config_dict.get("enabled", False):
        raise ElasticityConfigError(
            "Elasticity is disabled; set elasticity.enabled=true")
    elastic_config = ElasticityConfig(elastic_config_dict)

    if elastic_config.model_parallel_size > 1 and \
            float(elastic_config.version) != 0.2:
        raise ElasticityConfigError(
            f"Elasticity v{elastic_config.version} does not support "
            f"model parallelism (size {elastic_config.model_parallel_size})")
    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"elasticity version {elastic_config.version} > latest supported "
            f"{LATEST_ELASTICITY_VERSION}")
    if 'train_batch_size' in ds_config and not \
            elastic_config.ignore_non_elastic_batch_info:
        raise ElasticityConfigError(
            "train_batch_size in the config conflicts with elasticity; remove "
            "it or set elasticity.ignore_non_elastic_batch_info=true")

    micro_batch = None
    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            elastic_config.micro_batches,
            elastic_config.max_acceptable_batch_size,
            elastic_config.min_gpus, elastic_config.max_gpus,
            prefer_larger=True)
    elif float(elastic_config.version) == 0.2:
        if world_size != 0:
            current = world_size
        else:
            current = int(os.environ.get("WORLD_SIZE", 0))
        if current == 0:
            raise ElasticityConfigError(
                "elasticity v0.2 needs the current world size (arg or "
                "WORLD_SIZE env)")
        final_batch_size, valid_gpus, micro_batch = _get_compatible_gpus_v02(
            elastic_config.micro_batches,
            elastic_config.max_acceptable_batch_size,
            current_num_gpus=current,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=True,
            num_gpus_per_node=elastic_config.num_gpus_per_node,
            model_parallel_size=elastic_config.model_parallel_size)
    else:
        raise ElasticityConfigError(
            f"unknown elasticity version {elastic_config.version}")

    logger.info(f"elasticity: final batch size {final_batch_size}, "
                f"valid chip counts {valid_gpus}")
    # v0.2 returns valid *DP* world sizes; the caller's world_size is chips
    effective_ws = world_size // elastic_config.model_parallel_size \
        if float(elastic_config.version) == 0.2 else world_size
    if world_size > 0 and effective_ws not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} (dp {effective_ws}) is not compatible; "
            f"valid counts: {valid_gpus}")
    if return_microbatch:
        if micro_batch is None and world_size > 0:
            for m in sorted(elastic_config.micro_batches, reverse=True):
                if (final_batch_size // world_size) % m == 0:
                    micro_batch = m
                    break
        return final_batch_size, valid_gpus, micro_batch
    return final_batch_size, valid_gpus
