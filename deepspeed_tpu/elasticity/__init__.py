from .config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from .elastic_agent import DSElasticAgent, WorkerSpec
from .elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
)

__all__ = [
    "ElasticityConfig", "ElasticityConfigError", "ElasticityError",
    "ElasticityIncompatibleWorldSize", "DSElasticAgent", "WorkerSpec",
    "compute_elastic_config", "elasticity_enabled",
    "ensure_immutable_elastic_config",
]
