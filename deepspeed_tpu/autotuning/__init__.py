from .autotuner import Autotuner, run_autotuning
from .config import AutotuningConfig
from .tuner import CostModel, GridSearchTuner, ModelBasedTuner, RandomTuner

__all__ = ["Autotuner", "run_autotuning", "AutotuningConfig",
           "GridSearchTuner", "RandomTuner", "ModelBasedTuner", "CostModel"]
