from .autotuner import Autotuner, run_autotuning
from .config import AutotuningConfig
from .scheduler import Node, Reservation, ResourceManager
from .tuner import CostModel, GridSearchTuner, ModelBasedTuner, RandomTuner

__all__ = ["Autotuner", "run_autotuning", "AutotuningConfig",
           "GridSearchTuner", "RandomTuner", "ModelBasedTuner", "CostModel",
           "ResourceManager", "Node", "Reservation"]
