"""Autotuning config.

Capability parity with reference ``deepspeed/autotuning/config.py`` — the
``autotuning`` JSON block controlling the experiment search.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..runtime.config_utils import DeepSpeedConfigModel

AUTOTUNING_METRIC_LATENCY = "latency"
AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_FLOPS = "flops"

GRIDSEARCH_TUNER = "gridsearch"
RANDOM_TUNER = "random"
MODEL_BASED_TUNER = "model_based"


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    metric: str = AUTOTUNING_METRIC_THROUGHPUT
    start_profile_step: int = 3
    end_profile_step: int = 5
    metric_path: Optional[str] = None
    tuner_type: str = GRIDSEARCH_TUNER
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Optional[Dict[str, str]] = None
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: Optional[int] = None
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    mp_size: int = 1
