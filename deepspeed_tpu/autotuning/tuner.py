"""Experiment tuners.

Capability parity with reference ``deepspeed/autotuning/tuner/`` —
``GridSearchTuner`` / ``RandomTuner`` (random_tuner.py) /
``ModelBasedTuner`` (model_based_tuner.py with its xgboost cost model;
xgboost is not in the TPU image, so the cost model is a least-squares
quadratic over the numeric experiment features — same role: rank untried
points by predicted metric and explore best-first).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Experiment = Dict[str, Any]


class BaseTuner:
    def __init__(self, exps: List[Experiment],
                 metric_fn: Callable[[Experiment], Optional[float]],
                 early_stopping: int = 5):
        self.all_exps = list(exps)
        self.metric_fn = metric_fn
        self.early_stopping = early_stopping
        self.best_exp: Optional[Experiment] = None
        self.best_metric: float = float("-inf")
        self.records: List[Tuple[Experiment, Optional[float]]] = []

    def _order(self) -> List[Experiment]:
        raise NotImplementedError

    def tune(self) -> Tuple[Optional[Experiment], float]:
        stale = 0
        for exp in self._order():
            metric = self.metric_fn(exp)
            self.records.append((exp, metric))
            if metric is not None and metric > self.best_metric:
                self.best_metric = metric
                self.best_exp = exp
                stale = 0
            else:
                stale += 1
                if stale >= self.early_stopping:
                    break
        return self.best_exp, self.best_metric


class GridSearchTuner(BaseTuner):
    def _order(self):
        return self.all_exps


class RandomTuner(BaseTuner):
    def __init__(self, *args, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self._rng = random.Random(seed)

    def _order(self):
        order = list(self.all_exps)
        self._rng.shuffle(order)
        return order


def _features(exp: Experiment) -> List[float]:
    feats = []
    cfg = exp.get("ds_config", exp)
    feats.append(float(cfg.get("train_micro_batch_size_per_gpu", 1)))
    feats.append(float(cfg.get("gradient_accumulation_steps", 1)))
    feats.append(float(cfg.get("zero_optimization", {}).get("stage", 0)))
    return feats


class CostModel:
    """Least-squares quadratic surrogate over experiment features —
    stands in for the reference's xgboost cost model."""

    def __init__(self):
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._w = None

    @staticmethod
    def _expand(f: List[float]) -> List[float]:
        out = [1.0] + f
        out += [a * b for i, a in enumerate(f) for b in f[i:]]
        return out

    def fit(self, X: List[List[float]], y: List[float]) -> None:
        self._X, self._y = X, y
        if len(X) >= 3:
            A = np.asarray([self._expand(f) for f in X])
            self._w, *_ = np.linalg.lstsq(A, np.asarray(y), rcond=None)

    def predict(self, f: List[float]) -> float:
        if self._w is None:
            return 0.0
        return float(np.dot(self._expand(f), self._w))


class ModelBasedTuner(BaseTuner):
    """Explore a seed sample, fit the cost model, then try remaining points
    best-predicted-first (reference model_based_tuner.py flow)."""

    def __init__(self, *args, seed_trials: int = 3, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed_trials = seed_trials
        self.cost_model = CostModel()

    def tune(self):
        stale = 0
        pending = list(self.all_exps)
        tried: List[Experiment] = []
        X: List[List[float]] = []
        y: List[float] = []

        def run(exp) -> bool:
            nonlocal stale
            metric = self.metric_fn(exp)
            self.records.append((exp, metric))
            tried.append(exp)
            if metric is not None:
                X.append(_features(exp))
                y.append(metric)
            if metric is not None and metric > self.best_metric:
                self.best_metric = metric
                self.best_exp = exp
                stale = 0
                return True
            stale += 1
            return stale < self.early_stopping

        for exp in pending[:self.seed_trials]:
            if not run(exp):
                return self.best_exp, self.best_metric
        remaining = pending[self.seed_trials:]
        while remaining:
            self.cost_model.fit(X, y)
            remaining.sort(key=lambda e: -self.cost_model.predict(
                _features(e)))
            exp = remaining.pop(0)
            if not run(exp):
                break
        return self.best_exp, self.best_metric
