"""Experiment tuners.

Capability parity with reference ``deepspeed/autotuning/tuner/`` —
``GridSearchTuner`` / ``RandomTuner`` (random_tuner.py) /
``ModelBasedTuner`` (model_based_tuner.py with its xgboost cost model,
cost_model.py:12). xgboost is not in the TPU image, so the cost model is
a from-scratch gradient-boosted regression-tree ensemble (numpy, squared
loss, shrinkage, depth-limited greedy splits — the same learner family
as the reference's XGBRegressor, minus its regularization frills), with
a least-squares quadratic fallback while there are too few observations
to grow trees. Same role either way: rank untried points by predicted
metric and explore best-first.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Experiment = Dict[str, Any]


class BaseTuner:
    def __init__(self, exps: List[Experiment],
                 metric_fn: Callable[[Experiment], Optional[float]],
                 early_stopping: int = 5):
        self.all_exps = list(exps)
        self.metric_fn = metric_fn
        self.early_stopping = early_stopping
        self.best_exp: Optional[Experiment] = None
        self.best_metric: float = float("-inf")
        self.records: List[Tuple[Experiment, Optional[float]]] = []

    def _order(self) -> List[Experiment]:
        raise NotImplementedError

    def tune(self) -> Tuple[Optional[Experiment], float]:
        stale = 0
        for exp in self._order():
            metric = self.metric_fn(exp)
            self.records.append((exp, metric))
            if metric is not None and metric > self.best_metric:
                self.best_metric = metric
                self.best_exp = exp
                stale = 0
            else:
                stale += 1
                if stale >= self.early_stopping:
                    break
        return self.best_exp, self.best_metric


class GridSearchTuner(BaseTuner):
    def _order(self):
        return self.all_exps


class RandomTuner(BaseTuner):
    def __init__(self, *args, seed: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self._rng = random.Random(seed)

    def _order(self):
        order = list(self.all_exps)
        self._rng.shuffle(order)
        return order


def _features(exp: Experiment) -> List[float]:
    feats = []
    cfg = exp.get("ds_config", exp)
    feats.append(float(cfg.get("train_micro_batch_size_per_gpu", 1)))
    feats.append(float(cfg.get("gradient_accumulation_steps", 1)))
    feats.append(float(cfg.get("zero_optimization", {}).get("stage", 0)))
    return feats


class _RegressionTree:
    """Depth-limited CART regression tree (greedy SSE splits)."""

    def __init__(self, max_depth: int = 3, min_leaf: int = 2):
        self.max_depth = max_depth
        self.min_leaf = min_leaf
        self._root = None

    def _build(self, X: np.ndarray, y: np.ndarray, depth: int):
        n = len(y)
        leaf = float(y.mean()) if n else 0.0
        if depth >= self.max_depth or n < 2 * self.min_leaf:
            return leaf
        base_sse = float(((y - y.mean()) ** 2).sum())
        best = None  # (gain, feature, threshold, mask)
        for j in range(X.shape[1]):
            col = X[:, j]
            for t in np.unique(col)[:-1]:
                mask = col <= t
                nl = int(mask.sum())
                if nl < self.min_leaf or n - nl < self.min_leaf:
                    continue
                yl, yr = y[mask], y[~mask]
                sse = float(((yl - yl.mean()) ** 2).sum()
                            + ((yr - yr.mean()) ** 2).sum())
                gain = base_sse - sse
                if best is None or gain > best[0]:
                    best = (gain, j, float(t), mask)
        if best is None or best[0] <= 1e-12:
            return leaf
        _, j, t, mask = best
        return (j, t,
                self._build(X[mask], y[mask], depth + 1),
                self._build(X[~mask], y[~mask], depth + 1))

    def fit(self, X: np.ndarray, y: np.ndarray) -> "_RegressionTree":
        self._root = self._build(X, y, 0)
        return self

    def predict_one(self, x: np.ndarray) -> float:
        node = self._root
        while isinstance(node, tuple):
            j, t, left, right = node
            node = left if x[j] <= t else right
        return node

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray([self.predict_one(x) for x in X])


class CostModel:
    """Gradient-boosted regression trees over experiment features — the
    reference's xgboost surrogate (autotuning/tuner/cost_model.py:12),
    implemented from scratch: squared-loss boosting with shrinkage.
    Falls back to a least-squares quadratic below ``min_tree_samples``
    observations (trees need data to split on)."""

    def __init__(self, n_trees: int = 50, learning_rate: float = 0.3,
                 max_depth: int = 3, min_tree_samples: int = 6):
        self.n_trees = n_trees
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.min_tree_samples = min_tree_samples
        self._base = 0.0
        self._boosted = False  # tree path fitted (possibly with 0 trees)
        self._trees: List[_RegressionTree] = []
        self._w = None  # quadratic fallback weights

    @staticmethod
    def _expand(f: List[float]) -> List[float]:
        out = [1.0] + f
        out += [a * b for i, a in enumerate(f) for b in f[i:]]
        return out

    def fit(self, X: List[List[float]], y: List[float]) -> None:
        self._trees, self._w, self._boosted = [], None, False
        if len(X) < 3:
            return
        Xa = np.asarray(X, np.float64)
        ya = np.asarray(y, np.float64)
        if len(X) < self.min_tree_samples:
            A = np.asarray([self._expand(f) for f in X])
            self._w, *_ = np.linalg.lstsq(A, ya, rcond=None)
            return
        self._base = float(ya.mean())
        self._boosted = True
        pred = np.full(len(ya), self._base)
        for _ in range(self.n_trees):
            resid = ya - pred
            if float((resid ** 2).mean()) < 1e-12:
                break
            tree = _RegressionTree(self.max_depth).fit(Xa, resid)
            step = tree.predict(Xa)
            if not np.any(step):
                break
            pred = pred + self.learning_rate * step
            self._trees.append(tree)

    def predict(self, f: List[float]) -> float:
        if self._boosted:  # 0 trees = flat metrics; the mean IS the fit
            x = np.asarray(f, np.float64)
            return self._base + self.learning_rate * sum(
                t.predict_one(x) for t in self._trees)
        if self._w is not None:
            return float(np.dot(self._expand(f), self._w))
        return 0.0


class ModelBasedTuner(BaseTuner):
    """Explore a seed sample, fit the cost model, then try remaining points
    best-predicted-first (reference model_based_tuner.py flow)."""

    def __init__(self, *args, seed_trials: int = 3, **kwargs):
        super().__init__(*args, **kwargs)
        self.seed_trials = seed_trials
        self.cost_model = CostModel()

    def tune(self):
        stale = 0
        pending = list(self.all_exps)
        tried: List[Experiment] = []
        X: List[List[float]] = []
        y: List[float] = []

        def run(exp) -> bool:
            nonlocal stale
            metric = self.metric_fn(exp)
            self.records.append((exp, metric))
            tried.append(exp)
            if metric is not None:
                X.append(_features(exp))
                y.append(metric)
            if metric is not None and metric > self.best_metric:
                self.best_metric = metric
                self.best_exp = exp
                stale = 0
                return True
            stale += 1
            return stale < self.early_stopping

        for exp in pending[:self.seed_trials]:
            if not run(exp):
                return self.best_exp, self.best_metric
        remaining = pending[self.seed_trials:]
        while remaining:
            self.cost_model.fit(X, y)
            remaining.sort(key=lambda e: -self.cost_model.predict(
                _features(e)))
            exp = remaining.pop(0)
            if not run(exp):
                break
        return self.best_exp, self.best_metric
