"""Autotuner.

Capability parity with reference ``deepspeed/autotuning/autotuner.py:42
Autotuner`` — profiles the model, generates ZeRO-stage × micro-batch
experiment grids from per-stage templates, runs them, and picks the best by
the configured metric. Reference experiments are cluster jobs scheduled by
a ResourceManager (autotuning/scheduler.py:33); the TPU-native primary mode
runs each experiment **in process** (build engine → few compiled steps →
measure), which is exact on a single host and avoids job-launch overhead.
A subprocess mode (``run_autotuning``, wired to ``--autotuning`` in the
launcher) re-runs the user script per experiment with the candidate config
and reads the metric file the engine drops (engine-side support: the
``autotuning`` config block's start/end profile steps).
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import log_dist, logger
from .config import (
    GRIDSEARCH_TUNER,
    MODEL_BASED_TUNER,
    RANDOM_TUNER,
    AutotuningConfig,
)
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

# per-stage config templates (reference autotuning/config_templates/*.json)
ZERO_STAGE_TEMPLATES: Dict[int, Dict[str, Any]] = {
    0: {"zero_optimization": {"stage": 0}},
    1: {"zero_optimization": {"stage": 1}},
    2: {"zero_optimization": {"stage": 2}},
    3: {"zero_optimization": {"stage": 3}},
}

DEFAULT_MIN_MEM_CONFIG = {"zero_optimization": {"stage": 3},
                          "memory_break_down": False}


class Autotuner:
    def __init__(self,
                 model_factory: Optional[Callable[[], Any]] = None,
                 batch_factory: Optional[Callable[[int], Any]] = None,
                 base_config: Optional[Dict[str, Any]] = None,
                 autotuning_config: Optional[Dict[str, Any]] = None,
                 mesh=None):
        self.model_factory = model_factory
        self.batch_factory = batch_factory
        self.base_config = dict(base_config or {})
        at = dict(self.base_config.get("autotuning", {}))
        at.update(autotuning_config or {})
        self.config = AutotuningConfig(**at)
        self.mesh = mesh
        self.results: List[Dict[str, Any]] = []
        self.best: Optional[Dict[str, Any]] = None

    # -- model profiling (reference autotuner.py:663,274) ----------------
    def model_info(self) -> Dict[str, float]:
        """Parameter count + rough per-stage memory needs (bytes/param):
        stage 0/1: 16 (fp16 p+g + fp32 p,m,v sharded differently), stage 2:
        grads sharded, stage 3: everything sharded. Mirrors the reference's
        activation-memory profiling at a coarser grain (XLA owns the
        activation schedule)."""
        assert self.model_factory is not None
        import jax

        model = self.model_factory()
        batch = self.batch_factory(1)
        rng = jax.random.PRNGKey(0)
        params = model.init({"params": rng, "dropout": rng}, batch)["params"]
        num_params = sum(int(np.prod(np.shape(l)))
                         for l in jax.tree_util.tree_leaves(params))
        return {"num_params": num_params,
                "param_mem_per_stage": {
                    0: 16 * num_params, 1: 12 * num_params,
                    2: 6 * num_params, 3: 2 * num_params}}

    # -- experiment generation (reference autotuner.py:304) --------------
    def _micro_batch_candidates(self) -> List[int]:
        lo = self.config.min_train_micro_batch_size_per_gpu
        hi = self.config.max_train_micro_batch_size_per_gpu or lo * 16
        n = self.config.num_tuning_micro_batch_sizes
        cands = sorted({int(v) for v in np.geomspace(max(lo, 1), max(hi, 1),
                                                     num=n).round()})
        return cands

    def _generate_experiments(self, stages: Optional[List[int]] = None
                              ) -> List[Dict[str, Any]]:
        stages = stages if stages is not None else [0, 1, 2, 3]
        exps = []
        for stage, mbs in itertools.product(stages,
                                            self._micro_batch_candidates()):
            ds_config = copy.deepcopy(self.base_config)
            ds_config.pop("autotuning", None)
            template = copy.deepcopy(ZERO_STAGE_TEMPLATES[stage])
            zo = dict(ds_config.get("zero_optimization", {}))
            zo.update(template["zero_optimization"])
            ds_config["zero_optimization"] = zo
            ds_config["train_micro_batch_size_per_gpu"] = mbs
            ds_config.pop("train_batch_size", None)
            exps.append({
                "name": f"z{stage}_mbs{mbs}",
                "ds_config": ds_config,
                "num_steps": self.config.end_profile_step,
            })
        return exps

    # -- experiment execution --------------------------------------------
    def run_experiment(self, exp: Dict[str, Any]) -> Optional[float]:
        """In-process: build an engine from the experiment config, run the
        profiled steps, return the metric (higher is better)."""
        import jax

        import deepspeed_tpu as ds
        from ..parallel import mesh as mesh_mod

        try:
            mesh_mod.reset_mesh()
            if self.mesh is not None:
                mesh_mod.set_mesh(self.mesh)
            model = self.model_factory()
            engine, _, _, _ = ds.initialize(model=model,
                                            config=exp["ds_config"])
            batch = self.batch_factory(engine.train_batch_size())
            start = self.config.start_profile_step
            end = max(exp.get("num_steps", self.config.end_profile_step),
                      start + 1)
            t0 = None
            for step in range(end):
                loss = engine.train_batch(batch=batch)
                if step + 1 == start:
                    jax.block_until_ready(loss)
                    t0 = time.perf_counter()
            jax.block_until_ready(loss)
            elapsed = time.perf_counter() - t0 if t0 else float("inf")
            steps_measured = end - start
            samples = steps_measured * engine.train_batch_size()
            throughput = samples / max(elapsed, 1e-9)
            latency = elapsed / max(steps_measured, 1)
            if self.config.metric == "latency":
                metric = -latency
            else:
                metric = throughput
            result = {"name": exp["name"], "ds_config": exp["ds_config"],
                      "throughput": throughput, "latency": latency,
                      "metric": metric}
            self.results.append(result)
            log_dist(f"autotuning exp {exp['name']}: "
                     f"{throughput:.1f} samples/s", ranks=[0])
            return metric
        except Exception as e:  # OOM / invalid combo → prune this point
            logger.warning(f"autotuning exp {exp['name']} failed: {e}")
            self.results.append({"name": exp["name"],
                                 "ds_config": exp["ds_config"],
                                 "error": str(e), "metric": None})
            return None

    # -- main entry (reference autotuner.py:404 tune) --------------------
    def tune(self, stages: Optional[List[int]] = None) -> Dict[str, Any]:
        exps = self._generate_experiments(stages)
        tuner_cls = {GRIDSEARCH_TUNER: GridSearchTuner,
                     RANDOM_TUNER: RandomTuner,
                     MODEL_BASED_TUNER: ModelBasedTuner}[
            self.config.tuner_type]
        tuner = tuner_cls(exps, self.run_experiment,
                          early_stopping=self.config.tuner_early_stopping)
        best_exp, best_metric = tuner.tune()
        if best_exp is not None:
            self.best = {"name": best_exp["name"],
                         "ds_config": best_exp["ds_config"],
                         "metric": best_metric}
        self._write_results()
        return self.best or {}

    def _write_results(self) -> None:
        os.makedirs(self.config.results_dir, exist_ok=True)
        with open(os.path.join(self.config.results_dir,
                               "autotuning_results.json"), "w") as f:
            json.dump(self.results, f, indent=2, default=str)
        if self.best:
            with open(os.path.join(self.config.results_dir,
                                   "best_config.json"), "w") as f:
                json.dump(self.best["ds_config"], f, indent=2)
        log_dist(f"autotuning: {len(self.results)} experiments, best = "
                 f"{self.best['name'] if self.best else None}", ranks=[0])


def run_autotuning(args, active_resources) -> None:
    """Launcher ``--autotuning`` entry (reference runner.py:353): schedules
    every experiment as a REAL subprocess run of the user script through the
    :class:`~deepspeed_tpu.autotuning.scheduler.ResourceManager` (candidate
    config injected via ``DS_AUTOTUNING_CONFIG``; the engine profiles the
    step window, writes metrics.json, and exits)."""
    # the ds config comes from --deepspeed_config (explicit, like the
    # reference); only if absent fall back to the first json in user_args
    base_config = {}
    user_args = list(getattr(args, "user_args", []))
    cfg_arg = None
    for i, arg in enumerate(user_args):
        if arg in ("--deepspeed_config", "--deepspeed-config") and \
                i + 1 < len(user_args):
            cfg_arg = user_args[i + 1]
            break
        if arg.startswith("--deepspeed_config=") or \
                arg.startswith("--deepspeed-config="):
            cfg_arg = arg.split("=", 1)[1]
            break
    if cfg_arg is None:
        cfg_arg = next((a for a in user_args
                        if a.endswith(".json") and os.path.isfile(a)), None)
    if cfg_arg and os.path.isfile(cfg_arg):
        with open(cfg_arg) as f:
            base_config = json.load(f)
    at_cfg = AutotuningConfig(**base_config.get("autotuning", {}))

    results_dir = at_cfg.results_dir
    os.makedirs(results_dir, exist_ok=True)
    tuner = Autotuner(base_config=base_config)
    exps = tuner._generate_experiments()
    for exp in exps:
        # DS_AUTOTUNING_EXIT makes the engine stop the run right after the
        # profile window — an experiment costs ~end_profile_step steps, not
        # a full training run
        exp["ds_config"].setdefault("autotuning", {})
        exp["ds_config"]["autotuning"].update(
            {"enabled": True,
             "start_profile_step": at_cfg.start_profile_step,
             "end_profile_step": at_cfg.end_profile_step})

    from .scheduler import ResourceManager

    # experiments execute as LOCAL subprocesses (remote-host dispatch is not
    # implemented): concurrency = the first host's slot count, never the
    # cluster-wide sum, or the local machine would be oversubscribed and the
    # measured metrics would be garbage
    resources = active_resources or {"localhost": 1}
    if len(resources) > 1:
        logger.warning(
            "autotuning experiments run on the local host only; using the "
            f"first of {len(resources)} hosts for the concurrency limit")
    first = next(iter(resources.values()))
    slots = max(1, len(first) if isinstance(first, (list, tuple))
                else int(first))
    manager = ResourceManager(
        hosts={"localhost": slots}, results_dir=results_dir,
        exps_dir=at_cfg.exps_dir, arg_mappings=at_cfg.arg_mappings,
        master_port=getattr(args, "master_port", 29500))
    manager.schedule_experiments(exps)
    finished = manager.run(args.user_script, list(args.user_args))

    from .scheduler import normalized_metric

    results = [{"name": e["name"],
                "metric": normalized_metric(e.get("metrics"), at_cfg.metric),
                "returncode": e.get("returncode"),
                "reservation": e.get("reservation")}
               for e in finished.values()]
    with open(os.path.join(results_dir, "autotuning_results.json"),
              "w") as f:
        json.dump(results, f, indent=2)
    best = manager.best(at_cfg.metric)
    if best:
        # best_config must NOT keep the injected experiment-mode autotuning
        # block (it would re-activate profiling in production runs) — the
        # manager already strips it
        with open(os.path.join(results_dir, "best_config.json"), "w") as f:
            json.dump(best["ds_config"], f, indent=2)
    logger.info(f"autotuning done; best = {best['name'] if best else None}")
