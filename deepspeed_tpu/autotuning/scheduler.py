"""Experiment scheduler — the reference ``autotuning/scheduler.py:33
ResourceManager`` analog.

Schedules tuning experiments as REAL runs: each experiment is the user
script launched in a subprocess with the candidate config injected via
``DS_AUTOTUNING_CONFIG`` (the engine reads it, profiles the configured step
window, writes ``metrics.json`` and exits under ``DS_AUTOTUNING_EXIT`` —
runtime/engine.py _after_step). The manager holds a pool of (host, slot)
reservations, runs as many experiments concurrently as there are idle slots
(threads; one slot per experiment), skips experiments whose results
already exist (resume), applies the reference's ``arg_mappings`` rewrite
of user CLI args with tuned values, and collects metrics for the tuner.

Differences from the reference, by design: slots are concurrency tokens on
the LOCAL host (one JAX process drives all local chips; experiments are
always local subprocesses — remote-host dispatch is not implemented, so
callers must size the pool to this machine). The subprocess path is
exercised end-to-end in tests/unit/autotuning.
"""

from __future__ import annotations

import copy
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..runtime.config_utils import get_nested as _get_by_dotted_key
from ..utils.logging import logger


def normalized_metric(metrics: Optional[Dict[str, Any]],
                      metric: str) -> Optional[float]:
    """Higher-is-better normalization shared by best() and the results
    file: latency flips sign, everything else is read as-is."""
    m = metrics or {}
    if metric == "latency":
        return -m["latency"] if "latency" in m else None
    return m.get(metric)


class Node:
    def __init__(self, host: str, slots: int):
        self.host = host
        self.max_slots = slots
        self.idle_slots: List[int] = list(range(slots))

    def reserve(self, n: int) -> Optional[List[int]]:
        if len(self.idle_slots) < n:
            return None
        taken, self.idle_slots = self.idle_slots[:n], self.idle_slots[n:]
        return taken

    def release(self, slots: Sequence[int]) -> None:
        self.idle_slots.extend(slots)


class Reservation:
    def __init__(self, node: Node, slots: List[int]):
        self.node = node
        self.slots = slots

    def release(self) -> None:
        self.node.release(self.slots)

    def __repr__(self):
        return f"{self.node.host}:{','.join(map(str, self.slots))}"




class ResourceManager:
    """≅ reference autotuning/scheduler.py:33 — queue + reservations +
    threaded experiment execution + result collection."""

    def __init__(self, hosts: Dict[str, int], results_dir: str,
                 exps_dir: str, arg_mappings: Optional[Dict[str, str]] = None,
                 master_port: int = 29500,
                 env: Optional[Dict[str, str]] = None):
        self.nodes = [Node(h, n) for h, n in hosts.items()]
        self.results_dir = results_dir
        self.exps_dir = exps_dir
        self.arg_mappings = dict(arg_mappings or {})
        self.master_port = master_port
        self.env = dict(env or {})
        self.experiment_queue: List[Dict[str, Any]] = []
        self.running: Dict[int, Tuple[threading.Thread, Dict, Reservation]] = {}
        self.finished: Dict[int, Dict[str, Any]] = {}
        self._count = 0
        self._lock = threading.Lock()

    # -- queueing ---------------------------------------------------------
    def schedule_experiments(self, exps: Sequence[Dict[str, Any]]) -> None:
        for exp in exps:
            exp = dict(exp)
            exp["exp_id"] = self._count
            self._count += 1
            result_dir = os.path.join(self.results_dir, exp["name"])
            exp["result_dir"] = result_dir
            metric_file = os.path.join(result_dir, "metrics.json")
            exp.setdefault("ds_config", {}).setdefault("autotuning", {})
            exp["ds_config"]["autotuning"]["metric_path"] = metric_file
            # resume: a finished experiment (metrics present) is not re-run
            if os.path.exists(metric_file):
                logger.info(f"skipping exp {exp['name']}: result exists")
                with open(metric_file) as f:
                    exp["metrics"] = json.load(f)
                exp["returncode"] = 0
                self.finished[exp["exp_id"]] = exp
                continue
            self.experiment_queue.append(exp)

    # -- reservations -----------------------------------------------------
    def _reserve(self, n_slots: int = 1) -> Optional[Reservation]:
        for node in self.nodes:
            slots = node.reserve(n_slots)
            if slots is not None:
                return Reservation(node, slots)
        return None

    # -- execution --------------------------------------------------------
    def _run_experiment(self, exp: Dict[str, Any], reservation: Reservation,
                        user_script: str, user_args: List[str]) -> None:
        try:
            self._run_experiment_inner(exp, reservation, user_script,
                                       user_args)
        except Exception as e:  # a worker failure must still be recorded
            logger.warning(f"exp {exp['name']} failed in scheduler: {e}")
            exp.setdefault("returncode", -1)
            exp["metrics"] = None
            exp["error"] = str(e)
            with self._lock:
                self.finished[exp["exp_id"]] = exp

    def _run_experiment_inner(self, exp: Dict[str, Any],
                              reservation: Reservation, user_script: str,
                              user_args: List[str]) -> None:
        result_dir = exp["result_dir"]
        exp["reservation"] = repr(reservation)
        os.makedirs(result_dir, exist_ok=True)
        exp_dir = os.path.join(self.exps_dir, exp["name"])
        os.makedirs(exp_dir, exist_ok=True)
        cfg_path = os.path.join(exp_dir, "ds_config.json")
        with open(cfg_path, "w") as f:
            json.dump(exp["ds_config"], f, indent=2)

        # reference arg_mappings: rewrite user CLI args with tuned values
        args = list(user_args)
        for key, arg_name in self.arg_mappings.items():
            val = _get_by_dotted_key(exp["ds_config"], key)
            if val is None or str(val) == "auto":
                continue
            if arg_name in args and args.index(arg_name) + 1 < len(args):
                args[args.index(arg_name) + 1] = str(val)
            else:
                if arg_name in args:  # dangling flag at the end
                    args.remove(arg_name)
                args += [arg_name, str(val)]

        env = dict(os.environ)
        env.update(self.env)
        env.update({
            "DS_AUTOTUNING_CONFIG": cfg_path,
            "DS_AUTOTUNING_EXIT": "1",
            "MASTER_PORT": str(self.master_port + exp["exp_id"]),
        })
        cmd = [sys.executable, "-u", user_script] + args
        t0 = time.perf_counter()
        with open(os.path.join(result_dir, "stdout.log"), "w") as out, \
                open(os.path.join(result_dir, "stderr.log"), "w") as err:
            proc = subprocess.run(cmd, env=env, stdout=out, stderr=err)
        exp["returncode"] = proc.returncode
        exp["wall_s"] = time.perf_counter() - t0
        metric_file = exp["ds_config"]["autotuning"]["metric_path"]
        if os.path.exists(metric_file):
            with open(metric_file) as f:
                exp["metrics"] = json.load(f)
        else:
            exp["metrics"] = None
        with self._lock:
            self.finished[exp["exp_id"]] = exp
        logger.info(f"exp {exp['name']} rc={proc.returncode} "
                    f"metrics={exp['metrics']}")

    def run(self, user_script: str, user_args: List[str],
            poll_s: float = 0.2) -> Dict[int, Dict[str, Any]]:
        """Drain the queue, keeping every idle slot busy (the reference's
        schedule/check loop)."""
        if sum(n.max_slots for n in self.nodes) < 1:
            raise ValueError("ResourceManager needs at least one slot "
                             f"(hosts={[(n.host, n.max_slots) for n in self.nodes]})")
        while self.experiment_queue or self.running:
            while self.experiment_queue:
                reservation = self._reserve(1)
                if reservation is None:
                    break
                exp = self.experiment_queue.pop(0)
                t = threading.Thread(
                    target=self._run_experiment,
                    args=(exp, reservation, user_script, list(user_args)),
                    daemon=True)
                t.start()
                self.running[exp["exp_id"]] = (t, exp, reservation)
            for exp_id in list(self.running):
                t, exp, reservation = self.running[exp_id]
                t.join(timeout=poll_s)
                if not t.is_alive():
                    reservation.release()
                    del self.running[exp_id]
        return self.finished

    # -- selection --------------------------------------------------------
    def best(self, metric: str = "throughput") -> Optional[Dict[str, Any]]:
        """Highest-is-better over finished experiments (latency flips sign,
        matching the in-process tuner)."""
        best = None
        for exp in self.finished.values():
            val = normalized_metric(exp.get("metrics"), metric)
            if val is None:
                continue
            if best is None or val > best[0]:
                best = (val, exp)
        if best is None:
            return None
        val, exp = best
        clean = copy.deepcopy(exp["ds_config"])
        clean.pop("autotuning", None)
        return {"name": exp["name"], "metric": val, "ds_config": clean,
                "metrics": exp.get("metrics")}
