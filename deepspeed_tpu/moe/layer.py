"""MoE layer (expert parallelism).

Capability parity with reference ``deepspeed/moe/layer.py:16 MoE`` +
``moe/experts.py:10 Experts``. TPU-native design:

* Experts live as one stacked parameter tree with a leading expert dimension
  (``nn.vmap``), sharded over the ``expert`` mesh axis — each device holds
  ``num_experts / ep_size`` local experts, exactly the reference's
  ``num_local_experts`` layout without per-rank module lists.
* Dispatch/combine: GShard einsums (``sharded_moe.py``); the all-to-all the
  reference issues explicitly (``_AllToAll``, moe/sharded_moe.py:90) is
  emitted by XLA from the sharding constraint that moves the dispatched
  tensor's expert dim onto the ``expert`` axis.
* Expert-group creation (``deepspeed/utils/groups.py:108,202``) is replaced
  by the mesh: ``ep_size`` is the mesh's expert-axis extent.
"""

from __future__ import annotations

from typing import Any, Optional, Type

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_mod
from .sharded_moe import (_capacity, combine_indexed, combine_output,
                          dispatch_indexed, expert_counts, gate_and_dispatch,
                          gate_decisions)


def moe_sharding_rules(prefix: str = ""):
    """TP-style rules placing stacked expert params on the expert axis."""
    E = mesh_mod.EXPERT_AXIS
    return [
        (rf"{prefix}experts/.*kernel", (E, None, None)),
        (rf"{prefix}experts/.*bias", (E, None)),
    ]


class ExpertMLP(nn.Module):
    """Default expert: 2-layer MLP (the reference's typical expert module)."""

    hidden_size: int
    intermediate_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.intermediate_size, dtype=self.dtype, name="fc1")(x)
        h = jax.nn.gelu(h, approximate=True)
        return nn.Dense(self.hidden_size, dtype=self.dtype, name="fc2")(h)


class MoE(nn.Module):
    """Mixture-of-experts wrapper (≅ reference moe/layer.py:16).

    ``__call__(x)`` with x (..., hidden) returns ``(out, aux_loss, exp_counts)``
    like the reference's MoE.forward.
    """

    hidden_size: int
    num_experts: int = 1
    ep_size: int = 1  # informational; actual EP degree = mesh expert axis
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    # Residual MoE (PR-MoE, arXiv:2201.05596; reference layer.py:77,116):
    # a dense expert-shaped MLP runs alongside the MoE and the two outputs
    # are blended by a learned per-token softmax coefficient
    use_residual: bool = False
    # "auto" (default, measured policy — BASELINE.md round-5 MoE rows):
    # "einsum" for k=1 (the dense one-hot dispatch is a bf16 MXU matmul
    # and beats the scatter at top-1 capacity) UNLESS the dense form's
    # (S,E,C) tensor would exceed ``auto_index_threshold`` elements
    # (it grows ~quadratically with S); "index" (scatter/gather, O(S·M))
    # for k>=2 — 1.19-1.21x the einsum form at the NLG recipe shape —
    # and for any k at long S. Routing is identical in all modes (both
    # forms consume the same GateDecisions).
    dispatch_mode: str = "auto"
    # max elements of the dense (S,E,C) form before "auto" forces the
    # index form. The einsum path materializes BOTH the fp32 combine and
    # the token-dtype dispatch tensor (live through backward), so budget
    # ~2x per element: 2^29 elements ≈ 2 GB combine + ~1-2 GB dispatch
    # per MoE layer
    auto_index_threshold: int = 2 ** 29
    expert_cls: Type[nn.Module] = ExpertMLP
    expert_kwargs: Optional[dict] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, used_token=None, deterministic: bool = True):
        """x (..., hidden); ``used_token`` (keyword-only, broadcastable to
        x's token dims) masks padding tokens out of top-1 routing and the
        aux loss (reference layer.py:100 forward arg → sharded_moe.py:202)."""
        orig_shape = x.shape
        M = orig_shape[-1]
        assert M == self.hidden_size
        tokens = x.reshape(-1, M)
        if used_token is not None:
            used_token = jnp.broadcast_to(
                used_token, orig_shape[:-1]).reshape(-1)

        gate_logits = nn.Dense(self.num_experts, use_bias=False, name="gate",
                               dtype=jnp.float32)(tokens.astype(jnp.float32))

        if self.dispatch_mode not in ("auto", "index", "einsum"):
            raise ValueError(f"dispatch_mode must be 'auto', 'index' or "
                             f"'einsum', got {self.dispatch_mode!r}")
        rng = self.make_rng("gating") if self.has_rng("gating") else None
        cap_factor = self.capacity_factor if not deterministic \
            else self.eval_capacity_factor
        dispatch_mode = self.dispatch_mode
        if dispatch_mode == "auto":
            S = tokens.shape[0]
            cap = S if not self.drop_tokens else _capacity(
                S, self.num_experts, self.k * cap_factor, self.min_capacity)
            dense_elems = S * self.num_experts * cap
            dispatch_mode = "einsum" if (
                self.k == 1 and dense_elems <= self.auto_index_threshold) \
                else "index"
        if dispatch_mode == "index":
            dec = gate_decisions(
                gate_logits, k=self.k, capacity_factor=cap_factor,
                min_capacity=self.min_capacity,
                noisy_gate_policy=(self.noisy_gate_policy
                                   if not deterministic else None),
                drop_tokens=self.drop_tokens, use_rts=self.use_rts, rng=rng,
                used_token=used_token)
            aux_loss = dec.aux_loss
            dispatched = dispatch_indexed(tokens, dec, self.num_experts)
            combine = None
        else:
            dec = None
            aux_loss, dispatched, combine = gate_and_dispatch(
                tokens, gate_logits, k=self.k, capacity_factor=cap_factor,
                min_capacity=self.min_capacity,
                noisy_gate_policy=(self.noisy_gate_policy
                                   if not deterministic else None),
                drop_tokens=self.drop_tokens, use_rts=self.use_rts, rng=rng,
                used_token=used_token)

        # Move expert dim onto the expert axis: XLA emits the all-to-all here
        # (≅ reference _AllToAll before expert compute, sharded_moe.py:90)
        mesh = mesh_mod.get_mesh()
        dispatched = jax.lax.with_sharding_constraint(
            dispatched, NamedSharding(mesh, P(mesh_mod.EXPERT_AXIS, None, None)))

        kwargs = dict(self.expert_kwargs or {})
        kwargs.setdefault("hidden_size", self.hidden_size)
        kwargs.setdefault("intermediate_size", 4 * self.hidden_size)
        kwargs.setdefault("dtype", self.dtype)
        experts = nn.vmap(
            self.expert_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=0, out_axes=0,
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(**kwargs, name="experts")
        expert_out = experts(dispatched)  # (E, C, M)

        # all-to-all back before combine
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(mesh_mod.EXPERT_AXIS, None, None)))
        if dispatch_mode == "index":
            out = combine_indexed(expert_out, dec)
            exp_counts = expert_counts(dec, self.num_experts)
        else:
            out = combine_output(expert_out, combine)
            exp_counts = jnp.sum(combine > 0, axis=(0, 2))  # tokens per expert

        if self.use_residual:
            # PR-MoE: out = coef0 * moe_out + coef1 * dense_mlp(x), with
            # coef = softmax(Linear(hidden, 2)(x)) per token
            mlp_out = self.expert_cls(**kwargs, name="residual_mlp")(tokens)
            coef = nn.Dense(2, dtype=jnp.float32, name="coefficient")(
                tokens.astype(jnp.float32))
            coef = jax.nn.softmax(coef, axis=-1).astype(out.dtype)
            out = out * coef[:, 0:1] + mlp_out.astype(out.dtype) * coef[:, 1:2]

        return out.reshape(orig_shape).astype(x.dtype), aux_loss, exp_counts
