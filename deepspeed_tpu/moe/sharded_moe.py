"""GShard-style top-1/top-2 gating and MoE dispatch math.

Capability parity with reference ``deepspeed/moe/sharded_moe.py`` —
``top1gating`` (:179), ``top2gating`` (:277), ``MOELayer`` dispatch/combine
einsums (:420,472), ``_AllToAll`` (:90) — as pure jnp. The gating math is the
public GShard algorithm (capacity, random token priority, load-balance aux
loss) and ports directly to tensor code.

TPU-native dispatch: the reference wraps an explicit NCCL all-to-all in an
autograd Function. Here the dispatched tensor gets a *sharding constraint*
(expert axis on dim 0) and XLA inserts the all-to-all over ICI — see
``layer.py``. Expert-data-parallel gradient reduction (reference
engine.py:2304 expert-grad groups) also falls out declaratively: expert
params are sharded over the ``expert`` axis, so their grads reduce only over
the remaining (data, seq) axes.

Two dispatch materializations share one gating core (:class:`GateDecisions`):

* ``einsum`` — the reference's dense one-hot form,
  ``einsum("sec,sm->ecm")`` (sharded_moe.py:420). Costs S·E·C·M MACs each
  way and materializes the (S,E,C) combine tensor; at NLG-recipe shapes
  (S=16k, E=8, cf=1.25 top-2) that is ~2.5x the expert FFN FLOPs and a
  multi-GB intermediate.
* ``index`` (default) — TPU-native scatter/gather: tokens are scattered
  into their (expert, slot) rows and gathered back with gate weights,
  O(S·M) memory traffic and no (S,E,C) tensor. Both paths consume the SAME
  decisions, so routing is identical by construction (parity-tested in
  ``tests/unit/moe/test_moe.py``).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GateDecisions(NamedTuple):
    """Routing decisions for a batch of S tokens under top-k gating.

    ``expert_idx``/``slot``/``gate``/``valid`` are (S, k): for each token
    and choice j, the expert it routes to, its slot in that expert's
    capacity buffer, its (top-2: renormalized) combine weight, and whether
    it survived the capacity cut. ``aux_loss`` is the load-balance loss
    (computed pre-capacity, as the reference does)."""

    aux_loss: jnp.ndarray
    expert_idx: jnp.ndarray   # (S, k) int32
    slot: jnp.ndarray         # (S, k) int32
    gate: jnp.ndarray         # (S, k) float32
    valid: jnp.ndarray        # (S, k) bool
    capacity: int


def _one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """≅ reference _capacity (sharded_moe.py): tokens/experts × factor."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _uniform_noise(rng, shape, eps: float = 1e-2):
    return jax.random.uniform(rng, shape, minval=1.0 - eps, maxval=1.0 + eps)


def _gumbel_noise(rng, shape):
    return jax.random.gumbel(rng, shape)


def top1_decisions(logits: jnp.ndarray,
                   capacity_factor: float = 1.0,
                   min_capacity: int = 4,
                   noisy_gate_policy: Optional[str] = None,
                   drop_tokens: bool = True,
                   use_rts: bool = True,
                   rng: Optional[jax.Array] = None,
                   used_token: Optional[jnp.ndarray] = None) -> GateDecisions:
    """Top-1 routing decisions (≅ reference sharded_moe.py:179).

    Random token selection (``use_rts``) breaks position bias when dropping.
    ``used_token`` (S,) masks padding tokens out of routing and the aux
    loss (reference sharded_moe.py:202-203; top-1 only, as there).
    """
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = S

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        logits_for_selection = logits + _gumbel_noise(sub, logits.shape)
    else:
        logits_for_selection = logits

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(logits_for_selection, axis=1)
    mask1 = _one_hot(indices1, E)  # (S, E)
    if used_token is not None:
        mask1 = mask1 * used_token.astype(mask1.dtype)[:, None]

    # load-balancing aux loss: E * mean_e(fraction_tokens_e * mean_gate_e)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    # random token priority: permute intra-expert ordering before capacity cut
    if use_rts and rng is not None:
        rng, sub = jax.random.split(rng)
        priority = jax.random.uniform(sub, (S,))
    else:
        priority = -jnp.arange(S, dtype=jnp.float32)  # FIFO order
    # position of each token within its expert queue, ordered by priority
    order = jnp.argsort(-priority)
    mask1_sorted = mask1[order]
    locations_sorted = jnp.cumsum(mask1_sorted, axis=0) - 1.0
    inv = jnp.argsort(order)
    locations1 = jnp.sum(locations_sorted[inv] * mask1, axis=1)  # (S,)

    keep = (locations1 < capacity) & (jnp.sum(mask1, axis=1) > 0)
    gates1 = jnp.sum(gates * mask1, axis=1)  # gate value of chosen expert

    return GateDecisions(
        aux_loss=aux_loss,
        expert_idx=indices1.astype(jnp.int32)[:, None],
        slot=locations1.astype(jnp.int32)[:, None],
        gate=gates1[:, None],
        valid=keep[:, None],
        capacity=capacity)


def top2_decisions(logits: jnp.ndarray,
                   capacity_factor: float = 1.0,
                   min_capacity: int = 4,
                   drop_tokens: bool = True,
                   rng: Optional[jax.Array] = None) -> GateDecisions:
    """Top-2 routing decisions (≅ reference sharded_moe.py:277): second
    expert chosen with gumbel noise, gates renormalized over the two picks
    (after the capacity cut, so a dropped first choice passes full weight
    to the surviving second — the reference's order of operations)."""
    S, E = logits.shape
    capacity = _capacity(S, E, 2 * capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = S

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)

    if rng is not None:
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits + _gumbel_noise(sub, logits.shape)
    else:
        logits_w_noise = logits
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits_w_noise)
    indices2 = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2, E)

    locations1 = jnp.cumsum(mask1, axis=0) - 1.0
    # second-choice tokens queue after all first choices
    locations2 = jnp.cumsum(mask2, axis=0) - 1.0 + jnp.sum(mask1, axis=0)[None, :]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    loc1 = jnp.sum(locations1 * mask1, axis=1)
    loc2 = jnp.sum(locations2 * mask2, axis=1)
    valid1 = loc1 < capacity
    valid2 = loc2 < capacity

    gates1 = jnp.sum(gates * mask1, axis=1) * valid1
    gates2 = jnp.sum(gates * mask2, axis=1) * valid2
    denom = jnp.maximum(gates1 + gates2, jnp.finfo(gates.dtype).eps)
    gates1, gates2 = gates1 / denom, gates2 / denom

    return GateDecisions(
        aux_loss=aux_loss,
        expert_idx=jnp.stack([indices1, indices2], axis=1).astype(jnp.int32),
        slot=jnp.stack([loc1, loc2], axis=1).astype(jnp.int32),
        gate=jnp.stack([gates1, gates2], axis=1),
        valid=jnp.stack([valid1, valid2], axis=1),
        capacity=capacity)


def gate_decisions(logits: jnp.ndarray, k: int = 1,
                   capacity_factor: float = 1.0, min_capacity: int = 4,
                   noisy_gate_policy: Optional[str] = None,
                   drop_tokens: bool = True, use_rts: bool = True,
                   rng: Optional[jax.Array] = None,
                   used_token: Optional[jnp.ndarray] = None) -> GateDecisions:
    """Top-k routing decisions (dispatcher over top1/top2). ``used_token``
    applies to top-1 only (the reference's TopKGate likewise forwards it
    only to top1gating, sharded_moe.py:406)."""
    if k == 1:
        return top1_decisions(logits, capacity_factor, min_capacity,
                              noisy_gate_policy, drop_tokens, use_rts, rng,
                              used_token=used_token)
    if k == 2:
        return top2_decisions(logits, capacity_factor, min_capacity,
                              drop_tokens, rng)
    raise ValueError(f"top-{k} gating unsupported (reference supports k=1,2)")


def _densify(dec: GateDecisions, num_experts: int, dtype
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Decisions → dense (combine (S,E,C), dispatch (S,E,C)) one-hot form."""
    S, k = dec.expert_idx.shape
    combine = jnp.zeros((S, num_experts, dec.capacity), jnp.float32)
    for j in range(k):
        maskj = _one_hot(dec.expert_idx[:, j], num_experts) \
            * dec.valid[:, j].astype(jnp.float32)[:, None]
        loc_oh = _one_hot(dec.slot[:, j], dec.capacity)
        combine = combine + (dec.gate[:, j][:, None, None]
                             * maskj[:, :, None] * loc_oh[:, None, :])
    dispatch = combine > 0
    return combine.astype(dtype), dispatch


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               use_rts: bool = True,
               rng: Optional[jax.Array] = None,
               used_token: Optional[jnp.ndarray] = None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Top-1 gating, dense form (≅ reference sharded_moe.py:179).

    Returns (aux_loss, combine_weights (S,E,C), dispatch_mask (S,E,C), capacity).
    """
    dec = top1_decisions(logits, capacity_factor, min_capacity,
                         noisy_gate_policy, drop_tokens, use_rts, rng,
                         used_token=used_token)
    combine, dispatch = _densify(dec, logits.shape[1], logits.dtype)
    return dec.aux_loss, combine, dispatch, dec.capacity


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Top-2 gating, dense form (≅ reference sharded_moe.py:277)."""
    dec = top2_decisions(logits, capacity_factor, min_capacity,
                         drop_tokens, rng)
    combine, dispatch = _densify(dec, logits.shape[1], logits.dtype)
    return dec.aux_loss, combine, dispatch, dec.capacity


def dispatch_indexed(tokens: jnp.ndarray, dec: GateDecisions,
                     num_experts: int) -> jnp.ndarray:
    """tokens (S, M) → dispatched (E, C, M) by scatter-add into (expert,
    slot) rows. O(S·M) memory traffic; replaces the S·E·C·M dispatch
    einsum (reference sharded_moe.py:420). Invalid/zero-gate tokens land
    in a pad row that is sliced off (mirrors ``dispatch = combine > 0``)."""
    S, M = tokens.shape
    E, C = num_experts, dec.capacity
    flat = jnp.zeros((E * C + 1, M), tokens.dtype)
    for j in range(dec.expert_idx.shape[1]):
        p = dec.expert_idx[:, j] * C + dec.slot[:, j]
        keep = dec.valid[:, j] & (dec.gate[:, j] > 0)
        p = jnp.where(keep, p, E * C)
        flat = flat.at[p].add(tokens)
    return flat[:E * C].reshape(E, C, M)


def combine_indexed(expert_out: jnp.ndarray, dec: GateDecisions) -> jnp.ndarray:
    """expert outputs (E, C, M) → (S, M) by gathering each token's
    (expert, slot) row(s) and weighting by its gate (reference's combine
    einsum, sharded_moe.py:472, without the (S,E,C) tensor)."""
    E, C, M = expert_out.shape
    flat = expert_out.reshape(E * C, M)
    S = dec.expert_idx.shape[0]
    out = jnp.zeros((S, M), expert_out.dtype)
    for j in range(dec.expert_idx.shape[1]):
        p = jnp.where(dec.valid[:, j],
                      dec.expert_idx[:, j] * C + dec.slot[:, j], 0)
        w = (dec.gate[:, j] * dec.valid[:, j]).astype(expert_out.dtype)
        out = out + w[:, None] * flat[p]
    return out


def expert_counts(dec: GateDecisions, num_experts: int) -> jnp.ndarray:
    """Tokens dispatched per expert (the reference's ``exp_counts``)."""
    counts = jnp.zeros((num_experts,), jnp.int32)
    for j in range(dec.expert_idx.shape[1]):
        keep = dec.valid[:, j] & (dec.gate[:, j] > 0)
        counts = counts + jnp.sum(
            _one_hot(dec.expert_idx[:, j], num_experts)
            * keep.astype(jnp.float32)[:, None], axis=0).astype(jnp.int32)
    return counts


def gate_and_dispatch(tokens: jnp.ndarray, gate_logits: jnp.ndarray, k: int = 1,
                      capacity_factor: float = 1.0, min_capacity: int = 4,
                      noisy_gate_policy: Optional[str] = None,
                      drop_tokens: bool = True, use_rts: bool = True,
                      rng: Optional[jax.Array] = None,
                      used_token: Optional[jnp.ndarray] = None):
    """tokens (S, M) + logits (S, E) → (aux_loss, dispatched (E, C, M),
    combine (S, E, C)). The dispatch einsum is the reference's
    ``einsum("sec,sm->ecm")`` (sharded_moe.py:420 area). Dense form; the
    MoE layer's default is the indexed form (``gate_decisions`` +
    ``dispatch_indexed``/``combine_indexed``)."""
    if k == 1:
        aux, combine, dispatch, _ = top1gating(
            gate_logits, capacity_factor, min_capacity, noisy_gate_policy,
            drop_tokens, use_rts, rng, used_token=used_token)
    elif k == 2:
        aux, combine, dispatch, _ = top2gating(
            gate_logits, capacity_factor, min_capacity, drop_tokens, rng)
    else:
        raise ValueError(f"top-{k} gating unsupported (reference supports k=1,2)")
    dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype), tokens)
    return aux, dispatched, combine


def combine_output(expert_out: jnp.ndarray, combine: jnp.ndarray) -> jnp.ndarray:
    """(E, C, M) expert outputs × (S, E, C) combine weights → (S, M)
    (reference's ``einsum("sec,ecm->sm")``, sharded_moe.py:472 area)."""
    return jnp.einsum("sec,ecm->sm", combine.astype(expert_out.dtype), expert_out)
