"""GShard-style top-1/top-2 gating and MoE dispatch math.

Capability parity with reference ``deepspeed/moe/sharded_moe.py`` —
``top1gating`` (:179), ``top2gating`` (:277), ``MOELayer`` dispatch/combine
einsums (:420,472), ``_AllToAll`` (:90) — as pure jnp. The gating math is the
public GShard algorithm (capacity, random token priority, load-balance aux
loss) and ports directly to tensor code.

TPU-native dispatch: the reference wraps an explicit NCCL all-to-all in an
autograd Function. Here the dispatched tensor gets a *sharding constraint*
(expert axis on dim 0) and XLA inserts the all-to-all over ICI — see
``layer.py``. Expert-data-parallel gradient reduction (reference
engine.py:2304 expert-grad groups) also falls out declaratively: expert
params are sharded over the ``expert`` axis, so their grads reduce only over
the remaining (data, seq) axes.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """≅ reference _capacity (sharded_moe.py): tokens/experts × factor."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _uniform_noise(rng, shape, eps: float = 1e-2):
    return jax.random.uniform(rng, shape, minval=1.0 - eps, maxval=1.0 + eps)


def _gumbel_noise(rng, shape):
    return jax.random.gumbel(rng, shape)


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               use_rts: bool = True,
               rng: Optional[jax.Array] = None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Top-1 gating (≅ reference sharded_moe.py:179).

    Returns (aux_loss, combine_weights (S,E,C), dispatch_mask (S,E,C), capacity).
    Random token selection (``use_rts``) breaks position bias when dropping.
    """
    S, E = logits.shape
    capacity = _capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = S

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        logits_for_selection = logits + _gumbel_noise(sub, logits.shape)
    else:
        logits_for_selection = logits

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(logits_for_selection, axis=1)
    mask1 = _one_hot(indices1, E)  # (S, E)

    # load-balancing aux loss: E * mean_e(fraction_tokens_e * mean_gate_e)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    # random token priority: permute intra-expert ordering before capacity cut
    if use_rts and rng is not None:
        rng, sub = jax.random.split(rng)
        priority = jax.random.uniform(sub, (S,))
    else:
        priority = -jnp.arange(S, dtype=jnp.float32)  # FIFO order
    # position of each token within its expert queue, ordered by priority
    order = jnp.argsort(-priority)
    mask1_sorted = mask1[order]
    locations_sorted = jnp.cumsum(mask1_sorted, axis=0) - 1.0
    inv = jnp.argsort(order)
    locations1 = jnp.sum(locations_sorted[inv] * mask1, axis=1)  # (S,)

    keep = (locations1 < capacity) & (jnp.sum(mask1, axis=1) > 0)
    mask1 = mask1 * keep[:, None]

    gates1 = jnp.sum(gates * mask1, axis=1)  # gate value of kept tokens
    loc_oh = _one_hot(locations1.astype(jnp.int32), capacity)  # (S, C)
    combine = gates1[:, None, None] * mask1[:, :, None] * loc_oh[:, None, :]
    dispatch = combine > 0
    return aux_loss, combine.astype(logits.dtype), dispatch, capacity


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               rng: Optional[jax.Array] = None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Top-2 gating (≅ reference sharded_moe.py:277): second expert chosen
    with gumbel noise, gates renormalized over the two picks."""
    S, E = logits.shape
    capacity = _capacity(S, E, 2 * capacity_factor, min_capacity)
    if not drop_tokens:
        capacity = S

    gates = jax.nn.softmax(logits, axis=1)
    indices1 = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1, E)

    if rng is not None:
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits + _gumbel_noise(sub, logits.shape)
    else:
        logits_w_noise = logits
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits_w_noise)
    indices2 = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2, E)

    locations1 = jnp.cumsum(mask1, axis=0) - 1.0
    # second-choice tokens queue after all first choices
    locations2 = jnp.cumsum(mask2, axis=0) - 1.0 + jnp.sum(mask1, axis=0)[None, :]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux_loss = jnp.sum(me * ce) * E

    loc1 = jnp.sum(locations1 * mask1, axis=1)
    loc2 = jnp.sum(locations2 * mask2, axis=1)
    mask1 = mask1 * (loc1 < capacity)[:, None]
    mask2 = mask2 * (loc2 < capacity)[:, None]

    gates1 = jnp.sum(gates * mask1, axis=1)
    gates2 = jnp.sum(gates * mask2, axis=1)
    denom = jnp.maximum(gates1 + gates2, jnp.finfo(gates.dtype).eps)
    gates1, gates2 = gates1 / denom, gates2 / denom

    loc1_oh = _one_hot(loc1.astype(jnp.int32), capacity)
    loc2_oh = _one_hot(loc2.astype(jnp.int32), capacity)
    combine1 = gates1[:, None, None] * mask1[:, :, None] * loc1_oh[:, None, :]
    combine2 = gates2[:, None, None] * mask2[:, :, None] * loc2_oh[:, None, :]
    combine = combine1 + combine2
    dispatch = combine > 0
    return aux_loss, combine.astype(logits.dtype), dispatch, capacity


def gate_and_dispatch(tokens: jnp.ndarray, gate_logits: jnp.ndarray, k: int = 1,
                      capacity_factor: float = 1.0, min_capacity: int = 4,
                      noisy_gate_policy: Optional[str] = None,
                      drop_tokens: bool = True, use_rts: bool = True,
                      rng: Optional[jax.Array] = None):
    """tokens (S, M) + logits (S, E) → (aux_loss, dispatched (E, C, M),
    combine (S, E, C)). The dispatch einsum is the reference's
    ``einsum("sec,sm->ecm")`` (sharded_moe.py:420 area)."""
    if k == 1:
        aux, combine, dispatch, _ = top1gating(
            gate_logits, capacity_factor, min_capacity, noisy_gate_policy,
            drop_tokens, use_rts, rng)
    elif k == 2:
        aux, combine, dispatch, _ = top2gating(
            gate_logits, capacity_factor, min_capacity, drop_tokens, rng)
    else:
        raise ValueError(f"top-{k} gating unsupported (reference supports k=1,2)")
    dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype), tokens)
    return aux, dispatched, combine


def combine_output(expert_out: jnp.ndarray, combine: jnp.ndarray) -> jnp.ndarray:
    """(E, C, M) expert outputs × (S, E, C) combine weights → (S, M)
    (reference's ``einsum("sec,ecm->sm")``, sharded_moe.py:472 area)."""
    return jnp.einsum("sec,ecm->sm", combine.astype(expert_out.dtype), expert_out)
