from .layer import ExpertMLP, MoE, moe_sharding_rules  # noqa: F401
from .sharded_moe import (  # noqa: F401
    GateDecisions,
    combine_indexed,
    combine_output,
    dispatch_indexed,
    expert_counts,
    gate_and_dispatch,
    gate_decisions,
    top1gating,
    top2gating,
)
