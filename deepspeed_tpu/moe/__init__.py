from .layer import ExpertMLP, MoE, moe_sharding_rules  # noqa: F401
from .sharded_moe import (  # noqa: F401
    combine_output,
    gate_and_dispatch,
    top1gating,
    top2gating,
)
