"""Low-overhead span tracer with Chrome trace-event / Perfetto export.

The tracer records *host-side* spans into a bounded, lock-protected
ring buffer. It is deliberately dumb: every event is a small dict, the
clock is ``time.perf_counter_ns`` (monotonic, ns resolution), and
nesting is never tracked explicitly — Chrome's trace viewer infers
nesting of complete ("X") events from ts/dur containment per thread
track, so a span stack on the host would only add overhead.

Disabled tracers hand out a shared null span so instrumented hot paths
pay one attribute load + one method call when tracing is off.

Event kinds emitted (Chrome trace-event ``ph`` codes):

* ``X`` — complete span (``span()`` context manager / ``trace()``
  decorator), with ``ts``/``dur`` in ns internally, µs on export.
* ``i`` — instant event (``instant()``).
* ``C`` — counter sample (``counter()``).
* ``b``/``n``/``e`` — async nestable events keyed by ``(cat, id)``;
  used for per-request lifecycle tracks (``async_begin`` /
  ``async_instant`` / ``async_end``).
* ``s``/``f`` — flow start/finish (``flow()``), drawing arrows from a
  request's track into the engine-step spans that serviced it.

Fleet export: :func:`merge_chrome` renders SEVERAL tracers into one
Chrome/Perfetto document — one *process* lane per tracer (pid = fleet
position, ``process_name`` metadata from the label), all timestamps
normalized to the fleet-wide earliest event. Because the tracers share
one host ``perf_counter_ns`` clock, cross-replica ordering is exact,
and a flow pair emitted on two different tracers with the same
``(cat, id)`` renders as an arrow ACROSS process lanes — the journey
arrows the router draws at every handoff/transfer/failover boundary.
"""

from __future__ import annotations

import functools
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


class _NullSpan:
    """No-op span returned by a disabled tracer; shared singleton."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0

    def set(self, **attrs):
        """Attach attributes to the span (visible in the trace viewer)."""
        if self.args is None:
            self.args = {}
        self.args.update(attrs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        now = time.perf_counter_ns()
        args = self.args
        if exc_type is not None:
            args = dict(args) if args else {}
            args["error"] = exc_type.__name__
        self._tracer._record({
            "name": self.name, "ph": "X", "ts": self._t0,
            "dur": now - self._t0,
            "tid": threading.get_ident(), "args": args,
        })
        return False


class Tracer:
    """Thread-safe bounded span recorder.

    ``capacity`` bounds host memory: once full, the oldest events are
    overwritten (ring buffer). ``events_total`` keeps counting, so
    ``events_total > capacity`` tells you the window wrapped.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 process_name: str = "deepspeed_tpu"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self.process_name = process_name
        self._lock = threading.Lock()
        self._buf: List[Dict[str, Any]] = []
        self._pos = 0  # next overwrite index once the buffer is full
        self.events_total = 0
        # wall-clock anchor so exports can be correlated across files
        self.epoch_ns = time.perf_counter_ns()
        self.epoch_unix = time.time()

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------
    def _record(self, ev: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        with self._lock:
            if len(self._buf) < self.capacity:
                self._buf.append(ev)
            else:
                self._buf[self._pos] = ev
                self._pos = (self._pos + 1) % self.capacity
            self.events_total += 1

    def span(self, name: str, **args):
        """Context manager timing a block: ``with tracer.span("x"): ...``"""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def trace(self, name: Optional[str] = None):
        """Decorator form of :meth:`span`."""
        def deco(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with self.span(label):
                    return fn(*a, **kw)
            return wrapper
        return deco

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        self._record({"name": name, "ph": "i",
                      "ts": time.perf_counter_ns(),
                      "tid": threading.get_ident(),
                      "s": "t", "args": args or None})

    def counter(self, name: str, **values) -> None:
        """Counter track sample, e.g. ``counter("slots", live=3)``."""
        if not self.enabled:
            return
        self._record({"name": name, "ph": "C",
                      "ts": time.perf_counter_ns(),
                      "tid": threading.get_ident(), "args": values})

    # --- async (per-request) tracks -----------------------------------
    def async_begin(self, cat: str, name: str, aid, **args) -> None:
        self._async("b", cat, name, aid, args)

    def async_instant(self, cat: str, name: str, aid, **args) -> None:
        self._async("n", cat, name, aid, args)

    def async_end(self, cat: str, name: str, aid, **args) -> None:
        self._async("e", cat, name, aid, args)

    def _async(self, ph: str, cat: str, name: str, aid,
               args: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        self._record({"name": name, "ph": ph, "cat": cat,
                      "id": aid, "ts": time.perf_counter_ns(),
                      "tid": threading.get_ident(),
                      "args": args or None})

    def flow(self, ph: str, name: str, fid, cat: str = "flow") -> None:
        """Flow event: ``ph`` is ``"s"`` (start) or ``"f"`` (finish)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": ph, "cat": cat, "id": fid,
              "ts": time.perf_counter_ns(), "tid": threading.get_ident()}
        if ph == "f":
            ev["bp"] = "e"  # bind to enclosing slice
        self._record(ev)

    # ------------------------------------------------------------------
    # inspection / export
    # ------------------------------------------------------------------
    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap-around (never exported)."""
        return max(0, self.events_total - self.capacity)

    def events(self) -> List[Dict[str, Any]]:
        """Snapshot of buffered events, oldest first."""
        with self._lock:
            return self._buf[self._pos:] + self._buf[:self._pos]

    def clear(self) -> None:
        with self._lock:
            self._buf = []
            self._pos = 0
            self.events_total = 0

    def to_chrome(self) -> Dict[str, Any]:
        """Render the buffer as a Chrome trace-event JSON object.

        Timestamps are normalized to µs relative to the earliest
        buffered event; thread idents are remapped to small tids so
        Perfetto's track names stay readable.
        """
        evs = self.events()
        base = min((e["ts"] for e in evs), default=0)
        tids: Dict[int, int] = {}
        out: List[Dict[str, Any]] = []
        for ev in evs:
            tid = tids.setdefault(ev.get("tid", 0), len(tids))
            o = {"name": ev["name"], "ph": ev["ph"], "pid": 0, "tid": tid,
                 "ts": (ev["ts"] - base) / 1e3}
            if "dur" in ev:
                o["dur"] = ev["dur"] / 1e3
            for k in ("cat", "id", "s", "bp"):
                if k in ev:
                    o[k] = ev[k]
            if ev.get("args"):
                o["args"] = ev["args"]
            out.append(o)
        meta = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": self.process_name}}]
        for ident, tid in tids.items():
            meta.append({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": tid, "args": {"name": f"host-{tid}"}})
        return {
            "traceEvents": meta + out,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix": self.epoch_unix,
                "events_total": self.events_total,
                "dropped": self.dropped,
            },
        }

    def export(self, path: str) -> int:
        """Write the Perfetto/Chrome JSON trace; returns event count."""
        trace = self.to_chrome()
        with open(path, "w") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])


# ---------------------------------------------------------------------------
# multi-process (fleet) merge
# ---------------------------------------------------------------------------
def merge_chrome(tracers: Sequence[Tuple[str, "Tracer"]]) -> Dict[str, Any]:
    """Merge several tracers into ONE Chrome trace-event document.

    ``tracers`` is an ordered ``(label, tracer)`` sequence; position in
    the sequence becomes the Perfetto *pid* and ``label`` its
    ``process_name`` — a DP fleet renders as one lane per replica (plus
    the router's own lane). Timestamps are normalized to the earliest
    event ACROSS the whole fleet: every tracer reads the same
    process-wide ``perf_counter_ns`` clock, so relative ordering
    between lanes is exact, and a flow ``s``/``f`` pair whose halves
    were recorded on two different tracers (same ``cat`` + ``id``)
    draws its arrow across the process boundary — how a request's
    handoff/transfer/failover hops stay visually connected.
    """
    snap = [(str(label), tr.events()) for label, tr in tracers]
    base = min((e["ts"] for _, evs in snap for e in evs), default=0)
    out: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    events_total = 0
    dropped = 0
    for pid, ((label, evs), (_, tr)) in enumerate(zip(snap, tracers)):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": label}})
        tids: Dict[int, int] = {}
        for ev in evs:
            tid = tids.setdefault(ev.get("tid", 0), len(tids))
            o = {"name": ev["name"], "ph": ev["ph"], "pid": pid,
                 "tid": tid, "ts": (ev["ts"] - base) / 1e3}
            if "dur" in ev:
                o["dur"] = ev["dur"] / 1e3
            for k in ("cat", "id", "s", "bp"):
                if k in ev:
                    o[k] = ev[k]
            if ev.get("args"):
                o["args"] = ev["args"]
            out.append(o)
        for tid in tids.values():
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": f"host-{tid}"}})
        events_total += tr.events_total
        dropped += tr.dropped
    return {
        "traceEvents": meta + out,
        "displayTimeUnit": "ms",
        "otherData": {
            "processes": {str(i): label
                          for i, (label, _) in enumerate(snap)},
            "events_total": events_total,
            "dropped": dropped,
        },
    }


def export_merged(path: str,
                  tracers: Sequence[Tuple[str, "Tracer"]]) -> int:
    """Write a :func:`merge_chrome` fleet trace; returns event count."""
    trace = merge_chrome(tracers)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
