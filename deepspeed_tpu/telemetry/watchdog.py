"""Recompile watchdog: turn "zero recompiles under churn" into a
runtime counter.

The serving tests pin the invariant that slot churn never triggers XLA
recompilation by diffing ``jitted._cache_size()`` before/after a wave.
This module promotes that into production telemetry:

* a process-global ``jax.monitoring`` duration listener counts every
  backend compile (``backend_compiles``, unattributed — JAX fires it
  for any program in the process);
* :class:`_WatchedJit` proxies wrap the named jitted entry points
  (``InferenceEngine._jit_*``, ``SlotPool._admit*_jit``); a call during
  which the global compile counter advanced is attributed a recompile
  under the program name plus the abstract shape signature of the
  offending call (``recompiles`` — the headline counter, counted after
  warmup).

Detection deliberately keys on the *backend compile* event, not on
``jitted._cache_size()`` growth: the C++ fastpath cache adds entries
for identical avals (e.g. numpy-backed vs device-resident inputs)
without lowering or compiling anything, so cache growth over-reports.
The compile-window attribution assumes watched programs are not called
concurrently from multiple threads (true for the serving/step loop);
a concurrent unrelated compile would at worst mislabel, never
undercount.

Each detection emits a ``telemetry/recompile`` event into the tracer,
registry, and monitor sinks. ``strict`` mode arms
:meth:`RecompileWatchdog.check` to raise
:class:`RecompileAfterWarmupError` — callers invoke it *between*
steps so an unexpected recompile aborts cleanly instead of corrupting
in-flight state.

``jax.monitoring`` listeners are global and cannot be removed
individually, so exactly one module-level listener is registered and
dispatches to a ``WeakSet`` of live watchdogs.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

try:  # pragma: no cover - jax is always present in this repo
    from jax import monitoring as _jax_monitoring
    from jax import tree_util as _jax_tree_util
except Exception:  # pragma: no cover
    _jax_monitoring = None
    _jax_tree_util = None


class RecompileAfterWarmupError(RuntimeError):
    """Raised by strict-mode watchdogs when a warmed program recompiles."""


# ----------------------------------------------------------------------
# shape signatures
# ----------------------------------------------------------------------
def _sig_one(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(map(str, shape))}]"
    if isinstance(x, (list, tuple, dict)):
        leaves: List[Any] = []
        if _jax_tree_util is not None:
            try:
                leaves = _jax_tree_util.tree_leaves(x)
            except Exception:
                leaves = []
        if leaves:
            return f"tree({len(leaves)} leaves, first={_sig_one(leaves[0])})"
        return f"{type(x).__name__}()"
    if isinstance(x, (bool, int, float, str)) or x is None:
        return repr(x)
    return type(x).__name__


def abstract_signature(args: tuple, kwargs: Dict[str, Any]) -> str:
    """Cheap human-readable abstraction of a call's arg shapes.

    Only computed when a recompile was already detected, so it can
    afford the pytree walk.
    """
    parts = [_sig_one(a) for a in args]
    parts += [f"{k}={_sig_one(v)}" for k, v in sorted(kwargs.items())]
    return "(" + ", ".join(parts) + ")"


def _manifest_one(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(map(str, shape))}]"
    if isinstance(x, (dict, list, tuple)):
        return "*"
    return repr(x)


def manifest_signature(args: tuple, kwargs: Dict[str, Any]) -> str:
    """The warmup-manifest rendering of one watched call: top-level
    only — arrays as ``dtype[d1,d2]``, pytree containers as ``*``,
    python scalars (static_argnums operands here) by ``repr``.

    This grammar is the runtime twin of
    ``deepspeed_tpu.analysis.absdomain.expand_signatures``; graftcheck
    diffs the two sets byte-for-byte, so any change here must be
    mirrored there (pinned by tests/unit/analysis/test_signatures.py).
    """
    parts = [_manifest_one(a) for a in args]
    parts += [f"{k}={_manifest_one(v)}" for k, v in sorted(kwargs.items())]
    return "(" + ", ".join(parts) + ")"


def _fast_one(x: Any) -> str:
    shape = getattr(x, "shape", None)
    if shape is not None:
        return f"{getattr(x, 'dtype', '?')}[{','.join(map(str, shape))}]"
    if isinstance(x, dict):
        return f"dict{len(x)}"
    if isinstance(x, (list, tuple)):
        return f"{type(x).__name__}{len(x)}"
    if isinstance(x, bool) or x is None or isinstance(x, str):
        return repr(x)
    if isinstance(x, (int, float)):
        # jit traces python scalars as weak-typed values, so the *value*
        # does not change the executable; keying on it would explode the
        # signature space (e.g. a chunk position argument).
        return type(x).__name__
    return type(x).__name__


def fast_signature(args: tuple, kwargs: Dict[str, Any]) -> str:
    """Value-independent top-level signature, cheap enough for every
    call: arrays by dtype/shape, containers by length, scalars by type.
    Unlike :func:`abstract_signature` this never walks pytrees, so it
    can key the per-call cost accounting inside the ≤3% telemetry
    overhead budget."""
    parts = [_fast_one(a) for a in args]
    if kwargs:
        parts += [f"{k}={_fast_one(v)}" for k, v in sorted(kwargs.items())]
    return "|".join(parts)


def _key_one(x: Any):
    # tuple-atom twin of _fast_one: raw shape/dtype objects are hashable
    # and skip every f-string, which matters at one key per watched call
    shape = getattr(x, "shape", None)
    if shape is not None:
        return (shape, getattr(x, "dtype", None))
    if isinstance(x, (dict, list, tuple)):
        return (type(x).__name__, len(x))
    if isinstance(x, (bool, str)) or x is None:
        return x
    return type(x).__name__


def fast_key(args: tuple, kwargs: Dict[str, Any]) -> tuple:
    """Hashable tuple equivalent of :func:`fast_signature` — same
    abstraction, no string formatting; the per-call cost-accounting key."""
    if kwargs:
        return (tuple(map(_key_one, args)),
                tuple((k, _key_one(v)) for k, v in sorted(kwargs.items())))
    return tuple(map(_key_one, args))


# ----------------------------------------------------------------------
# per-program proxies
# ----------------------------------------------------------------------
class _WatchedJit:
    """Transparent wrapper over a jitted callable: a call during which
    the process-wide backend-compile counter advanced is reported to
    the watchers as a recompile of this program.

    Attribute access falls through to the wrapped function, so
    existing ``fn._cache_size()`` call sites keep working whether or
    not the attribute has been wrapped. Non-jit callables (tests
    inject plain lambdas) trigger no compiles and pass through
    without bookkeeping.
    """

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name
        self._watchers: "weakref.WeakSet[RecompileWatchdog]" = \
            weakref.WeakSet()
        # ProgramCostModel instances accounting flops/bytes per call
        # (telemetry/costs.py); weak so dead servers drop off
        self._cost_models: "weakref.WeakSet" = weakref.WeakSet()
        # warmup signature manifest: every distinct manifest_signature
        # seen while recording (warmup); end_warmup() freezes it and the
        # frozen set is the runtime witness graftcheck diffs against
        self._manifest: set = set()
        self._recording = True
        _ensure_listener()

    def __call__(self, *args, **kwargs):
        if self._recording:
            self._manifest.add(manifest_signature(args, kwargs))
        start = _compile_events
        out = self._fn(*args, **kwargs)
        if _compile_events > start and self._watchers:
            sig = abstract_signature(args, kwargs)
            for w in list(self._watchers):
                w.record(self._name, sig)
        if self._cost_models:
            for cm in list(self._cost_models):
                cm.account(self._name, self._fn, args, kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self):
        return f"_WatchedJit({self._name}, {self._fn!r})"


# ----------------------------------------------------------------------
# global jax.monitoring listener
# ----------------------------------------------------------------------
_active_watchdogs: "weakref.WeakSet[RecompileWatchdog]" = weakref.WeakSet()
_listener_lock = threading.Lock()
_listener_registered = False
# process-wide backend-compile tick; _WatchedJit snapshots it around
# each call to attribute compiles to the program that triggered them
_compile_events = 0
# depth of suppress_compile_events() scopes: AOT cost harvesting
# (telemetry/costs.py) compiles the same program out-of-band, which
# must not register as a serving recompile
_suppress_compiles = 0


@contextlib.contextmanager
def suppress_compile_events():
    """Hide backend compiles from the watchdogs for the scope, e.g. the
    AOT ``lower().compile()`` the cost model runs to harvest
    ``cost_analysis()`` for an already-warm executable."""
    global _suppress_compiles
    _suppress_compiles += 1
    try:
        yield
    finally:
        _suppress_compiles -= 1


def _on_event_duration(event: str, duration: float, **kw) -> None:
    global _compile_events
    if "backend_compile" in event:
        if _suppress_compiles:
            return
        _compile_events += 1
        for w in list(_active_watchdogs):
            w._record_backend_compile(event, duration)


def _ensure_listener() -> None:
    global _listener_registered
    with _listener_lock:
        if _listener_registered or _jax_monitoring is None:
            return
        _jax_monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        _listener_registered = True


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------
class RecompileWatchdog:
    """Counts and attributes recompiles; optionally raises after warmup.

    Lifecycle: construct → :meth:`attach` the jitted entry points →
    run warmup traffic → :meth:`end_warmup` → steady state. Recompiles
    recorded before ``end_warmup()`` land in ``warmup_recompiles``;
    after it they land in the headline ``recompiles`` counter, and in
    ``strict`` mode the next :meth:`check` raises.
    """

    def __init__(self, registry=None, tracer=None, monitor=None,
                 strict: bool = False, step_fn=None, name: str = "",
                 cost_model=None):
        self.registry = registry
        self.tracer = tracer
        self.monitor = monitor
        self.strict = strict
        self.name = name
        # optional ProgramCostModel; attach() subscribes it to every
        # proxy so per-call flops/bytes accounting rides the same seam
        self.cost_model = cost_model
        self._step_fn = step_fn or (lambda: 0)
        self._warmed = False
        self.warmup_recompiles = 0
        self._post_warmup = 0
        self._raised_at = 0
        self.backend_compiles = 0
        self.events: List[Dict[str, Any]] = []
        # proxies this watchdog attached: the source of the warmup
        # signature manifest (weak — shared proxies outlive no owner)
        self._proxies: "weakref.WeakSet[_WatchedJit]" = weakref.WeakSet()
        _active_watchdogs.add(self)
        _ensure_listener()

    # -- wiring --------------------------------------------------------
    def attach(self, owner: Any, attr: str,
               name: Optional[str] = None) -> Optional[_WatchedJit]:
        """Wrap ``owner.attr`` (idempotent; proxies are shared across
        watchdogs so a jitted entry is never double-wrapped)."""
        fn = getattr(owner, attr, None)
        if fn is None:
            return None
        if isinstance(fn, _WatchedJit):
            proxy = fn
        else:
            proxy = _WatchedJit(
                fn, name or f"{type(owner).__name__}.{attr}")
            setattr(owner, attr, proxy)
        proxy._watchers.add(self)
        self._proxies.add(proxy)
        if self.cost_model is not None:
            proxy._cost_models.add(self.cost_model)
        return proxy

    def attach_all(self, owner: Any, attrs) -> None:
        for attr in attrs:
            self.attach(owner, attr)

    # -- recording -----------------------------------------------------
    def record(self, program: str, signature: str) -> None:
        warmup = not self._warmed
        self.events.append({
            "program": program, "signature": signature,
            "warmup": warmup, "time": time.time(),
        })
        if warmup:
            self.warmup_recompiles += 1
        else:
            self._post_warmup += 1
        if self.registry is not None:
            key = "telemetry/recompiles_warmup" if warmup \
                else "telemetry/recompiles"
            self.registry.counter(key).inc()
        if self.tracer is not None:
            self.tracer.instant("telemetry/recompile", program=program,
                                signature=signature, warmup=warmup)
        mon = self.monitor
        if mon is not None and getattr(mon, "enabled", False) and not warmup:
            mon.write_events([("telemetry/recompile",
                               float(self._post_warmup),
                               int(self._step_fn()))])

    def _record_backend_compile(self, event: str, duration: float) -> None:
        self.backend_compiles += 1
        if self.registry is not None:
            self.registry.counter("telemetry/backend_compiles").inc()

    # -- lifecycle -----------------------------------------------------
    def end_warmup(self) -> None:
        self._warmed = True
        # freeze the warmup manifest: signatures seen from here on are
        # post-warmup traffic, which the static checker must already
        # cover via the warmup set (that is the invariant under test)
        for p in list(self._proxies):
            p._recording = False

    def signature_manifest(self) -> Dict[str, List[str]]:
        """program name → sorted warmup signatures, across every proxy
        this watchdog attached (the runtime half of the graftcheck
        manifest diff)."""
        out: Dict[str, set] = {}
        for p in list(self._proxies):
            if p._manifest:  # a never-dispatched proxy has no warmup set
                out.setdefault(p._name, set()).update(p._manifest)
        return {name: sorted(sigs) for name, sigs in sorted(out.items())}

    @property
    def warmed(self) -> bool:
        return self._warmed

    @property
    def recompiles(self) -> int:
        """Attributed recompiles observed after :meth:`end_warmup`."""
        return self._post_warmup

    def check(self) -> None:
        """Raise (strict mode only) if a warmed program recompiled since
        the last check. Call between steps, never inside a step."""
        if (self.strict and self._warmed
                and self._post_warmup > self._raised_at):
            new = self.events[-1] if self.events else {}
            self._raised_at = self._post_warmup
            raise RecompileAfterWarmupError(
                f"recompile after warmup ({self._post_warmup} total): "
                f"{new.get('program', '?')} {new.get('signature', '')}")

    def summary(self) -> Dict[str, Any]:
        return {
            "recompiles": self._post_warmup,
            "warmup_recompiles": self.warmup_recompiles,
            "backend_compiles": self.backend_compiles,
            "warmed": self._warmed,
            "programs": sorted({e["program"] for e in self.events}),
        }
