"""Telemetry: structured tracing, per-request timelines, recompile
watchdog, and a Prometheus-exportable metrics registry.

This package is the TPU-idiomatic analogue of the reference
DeepSpeed's observability stack, mapped feature-for-feature:

* reference ``utils/timer.py`` (SynchronizedWallClockTimer) →
  :class:`Tracer` spans. The reference synchronizes CUDA before
  reading the clock; here the analogous hazard is JAX *async
  dispatch* — a host-side timer around a jitted call measures
  dispatch, not compute. Spans record honest host time; for compute
  time, pass the step outputs to ``Timer.stop(block_on=...)``
  (see ``utils/timer.py``) which ``block_until_ready``-s them first.
* reference ``monitor/`` (TensorBoard/WandB/csv scalar sinks) →
  :class:`MetricsRegistry` publishing ``(tag, value, step)`` events
  through the same ``MonitorMaster`` fan-out, plus the new machine-
  readable ``JSONLMonitor`` sink and Prometheus text exposition via
  :meth:`MetricsRegistry.to_prometheus`.
* reference ``flops_profiler`` (per-module latency breakdown) →
  step-phase spans inside ``ServingEngine.step()`` /
  ``DeepSpeedEngine.train_batch()`` exported as a Chrome
  trace-event / Perfetto JSON timeline (:meth:`Tracer.export`),
  including per-request lifecycle lanes (:class:`TimelineStore`) —
  per-iteration attribution rather than per-module FLOPs, because on
  TPU the profiler of record for intra-step FLOPs is XLA's own.
* no reference analogue: :class:`RecompileWatchdog`. XLA recompilation
  is the TPU-specific production hazard (a shape-churned serving step
  silently costs seconds); the watchdog attributes every recompile to
  a jitted program + abstract shape signature, and ``strict`` mode
  turns the tests' "zero recompiles under churn" invariant into a
  runtime guarantee.

Quick start::

    from deepspeed_tpu.telemetry import Tracer
    tracer = Tracer()
    with tracer.span("serving/step", step=3):
        ...
    tracer.export("/tmp/trace.json")   # open in ui.perfetto.dev

Serving integration (all knobs on ``ds.init_serving``)::

    srv = ds.init_serving(engine, tracer=Tracer(),
                          strict_recompile=True)
    srv.end_warmup()            # after warmup traffic
    srv.timeline(request_id)    # per-request lifecycle events
    srv.publish_telemetry()     # registry -> monitor sinks
"""

from .tracer import Tracer
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .timeline import TimelineStore
from .watchdog import (RecompileAfterWarmupError, RecompileWatchdog,
                       abstract_signature)

__all__ = [
    "Tracer",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "TimelineStore",
    "RecompileWatchdog",
    "RecompileAfterWarmupError",
    "abstract_signature",
]
