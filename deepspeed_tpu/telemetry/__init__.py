"""Telemetry: structured tracing, per-request timelines, recompile
watchdog, and a Prometheus-exportable metrics registry.

This package is the TPU-idiomatic analogue of the reference
DeepSpeed's observability stack, mapped feature-for-feature:

* reference ``utils/timer.py`` (SynchronizedWallClockTimer) →
  :class:`Tracer` spans. The reference synchronizes CUDA before
  reading the clock; here the analogous hazard is JAX *async
  dispatch* — a host-side timer around a jitted call measures
  dispatch, not compute. Spans record honest host time; for compute
  time, pass the step outputs to ``Timer.stop(block_on=...)``
  (see ``utils/timer.py``) which ``block_until_ready``-s them first.
* reference ``monitor/`` (TensorBoard/WandB/csv scalar sinks) →
  :class:`MetricsRegistry` publishing ``(tag, value, step)`` events
  through the same ``MonitorMaster`` fan-out, plus the new machine-
  readable ``JSONLMonitor`` sink and Prometheus text exposition via
  :meth:`MetricsRegistry.to_prometheus`.
* reference ``flops_profiler`` (per-module latency breakdown) →
  step-phase spans inside ``ServingEngine.step()`` /
  ``DeepSpeedEngine.train_batch()`` exported as a Chrome
  trace-event / Perfetto JSON timeline (:meth:`Tracer.export`),
  including per-request lifecycle lanes (:class:`TimelineStore`) —
  per-iteration attribution rather than per-module FLOPs, because on
  TPU the profiler of record for intra-step FLOPs is XLA's own.
* reference ``profiling/flops_profiler`` (module-walk MAC counting,
  ``get_model_profile``) → :class:`ProgramCostModel`
  (``telemetry/costs.py``). Where DeepSpeed re-derives flops from
  module hooks, XLA already knows: every warm executable's
  ``lowered.compile().cost_analysis()`` / ``memory_analysis()`` is
  harvested once per abstract signature through the ``_WatchedJit``
  seam and charged per call, yielding live MFU /
  bandwidth-utilization / tokens-per-flop gauges plus KV-HBM
  reconciliation (predicted page math vs actual device bytes,
  ``telemetry/hbm_drift``).
* no reference analogue: :class:`SLOTracker` (``telemetry/slo.py``) —
  O(1)-memory mergeable quantile digests over sliding windows,
  per-window goodput (finished-within-SLO ÷ admitted), and
  multi-window burn-rate alerting (``ok``/``warn``/``page``), the
  sensor suite the ROADMAP's SLO-aware scheduler consumes.
* no reference analogue: :class:`FlightRecorder`
  (``telemetry/flight_recorder.py``) — a bounded ring of per-step
  records that becomes a self-contained post-mortem JSON when the
  engine raises (invariant violation, stall, strict recompile), and a
  live ``srv.debug_dump()`` statusz snapshot.
* reference ``monitor/`` + flops profiler, fleet edition:
  :class:`FleetTelemetry` (``telemetry/fleet.py``). Where the
  reference fans ONE engine's scalars out to its sinks and profiles
  ONE module tree, the serving fleet needs the transpose — N replicas'
  registries merged into one Prometheus exposition with
  ``replica=``/``role=`` labels, per-replica quantile digests merged
  bucketwise into fleet p50/p99, goodput/burn computed over SUMMED
  admission windows, and ONE fleet post-mortem aligning every
  replica's flight-recorder ring on the shared injected clock.
  Cross-replica request *journeys* (minted by the router, stamped by
  each home's :class:`TimelineStore`, stitched by
  ``ReplicaRouter.journey``) play the flops profiler's attribution
  role at fleet scope: where a latency went, per hop, per replica —
  exported as a multi-process Perfetto document via
  :func:`merge_chrome` (one process lane per replica, flow arrows
  across handoff/transfer/failover boundaries).
* no reference analogue: :class:`RecompileWatchdog`. XLA recompilation
  is the TPU-specific production hazard (a shape-churned serving step
  silently costs seconds); the watchdog attributes every recompile to
  a jitted program + abstract shape signature, and ``strict`` mode
  turns the tests' "zero recompiles under churn" invariant into a
  runtime guarantee.

Quick start::

    from deepspeed_tpu.telemetry import Tracer
    tracer = Tracer()
    with tracer.span("serving/step", step=3):
        ...
    tracer.export("/tmp/trace.json")   # open in ui.perfetto.dev

Serving integration (all knobs on ``ds.init_serving``)::

    srv = ds.init_serving(engine, tracer=Tracer(),
                          strict_recompile=True)
    srv.end_warmup()            # after warmup traffic
    srv.timeline(request_id)    # per-request lifecycle events
    srv.publish_telemetry()     # registry -> monitor sinks
"""

from .tracer import Tracer, export_merged, merge_chrome
from .registry import Counter, Gauge, Histogram, MetricsRegistry
from .timeline import TimelineStore
from .watchdog import (RecompileAfterWarmupError, RecompileWatchdog,
                       abstract_signature, fast_signature,
                       suppress_compile_events)
from .costs import (ProgramCostModel, device_memory_report,
                    kv_hbm_report, resolve_peaks)
from .slo import (QuantileDigest, SLOConfig, SLOTargets, SLOTracker,
                  WindowedQuantiles)
from .flight_recorder import FlightRecorder, POST_MORTEM_KEYS
from .fleet import (FleetTelemetry, FLEET_POST_MORTEM_KEYS,
                    FLEET_SCHEMA_VERSION)

__all__ = [
    "Tracer",
    "merge_chrome",
    "export_merged",
    "FleetTelemetry",
    "FLEET_POST_MORTEM_KEYS",
    "FLEET_SCHEMA_VERSION",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "TimelineStore",
    "RecompileWatchdog",
    "RecompileAfterWarmupError",
    "abstract_signature",
    "fast_signature",
    "suppress_compile_events",
    "ProgramCostModel",
    "kv_hbm_report",
    "device_memory_report",
    "resolve_peaks",
    "QuantileDigest",
    "WindowedQuantiles",
    "SLOConfig",
    "SLOTargets",
    "SLOTracker",
    "FlightRecorder",
    "POST_MORTEM_KEYS",
]
