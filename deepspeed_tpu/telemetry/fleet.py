"""Fleet-scope telemetry plane: one merged view over N replicas.

PR 5/8 gave each :class:`~deepspeed_tpu.serving.engine.ServingEngine`
its own Tracer / TimelineStore / MetricsRegistry / SLOTracker /
FlightRecorder; PR 14/16 made the unit of deployment a FLEET behind a
:class:`~deepspeed_tpu.serving.router.ReplicaRouter`. This module is
the join: :class:`FleetTelemetry` wraps a router and renders the
fleet-level surfaces the frontend and benches consume —

* :meth:`to_prometheus` — ONE exposition merging every alive replica's
  registry. Router-owned series stay unlabeled (they are already
  fleet-scope); replica series gain ``replica="i",role="..."`` labels;
  ``fleet_*`` series are derived here by MERGING the per-replica SLO
  state — :class:`~.slo.QuantileDigest` rings add bucketwise (identical
  parameters), and goodput/burn come from SUMMED ``[admitted, good]``
  window pairs, which is mathematically the one tracker that saw every
  request (averaging per-replica burn rates is not: a replica with 2
  requests would weigh as much as one with 2000).
* :meth:`health_summary` — the ``/healthz`` fleet block: per-replica
  alert states and per-role queue depth / backlog (a decode role's
  backlog is the fleet's parked handoffs).
* :meth:`efficiency_snapshot` — fleet goodput, transfer-latency p99,
  journey completeness, and ``overhead_pct`` over the summed step wall
  (self-timed engine telemetry + the router's journey bookkeeping).
* :meth:`post_mortem` / :meth:`dump` — a fatal condition
  (``InvariantViolation`` / ``ServingStalledError`` / strict
  recompile) on ANY replica yields ONE fleet-scoped file: every
  replica's flight-recorder ring plus the router's journey/scale-event
  log, aligned on the shared injected clock (each engine step record
  carries ``t`` from the same ``clock``), with the triggering replica
  marked.

Everything here is host-side aggregation of already-recorded state —
zero jitted programs, no device traffic.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from .flight_recorder import _json_default
from .registry import Counter, Gauge, Histogram, _sanitize
from .slo import QuantileDigest

FLEET_SCHEMA_VERSION = 1

# keys every persisted fleet post-mortem carries; pinned by tests so
# external tooling can rely on the file shape
FLEET_POST_MORTEM_KEYS = ("schema_version", "reason", "error",
                          "time_unix", "t", "trigger_replica",
                          "fleet_size", "roles", "scale_events",
                          "journeys", "router", "replicas")

_ALERT_ORDER = {"ok": 0, "warn": 1, "page": 2}


class FleetTelemetry:
    """Merged observability surface over a :class:`ReplicaRouter`."""

    def __init__(self, router, dump_dir: Optional[str] = None):
        self.router = router
        self.dump_dir = dump_dir
        self.dumps: List[str] = []
        self.dump_failures = 0
        # digest merges refused for mismatched bucket parameters — a
        # misconfigured fleet shows up as a counter, not a lost scrape
        self.digest_merge_skipped = 0

    # -- iteration helpers ---------------------------------------------
    def _rows(self):
        """(index, role, replica) for every ALIVE replica."""
        r = self.router
        return [(i, r.roles[i], r.replicas[i]) for i in r.alive_replicas]

    # -- merged SLO state ----------------------------------------------
    def merged_digests(self) -> Dict[str, QuantileDigest]:
        """Fleet-wide ttft/gap/e2e digests: bucketwise sums of every
        replica's windowed rings. Replicas whose digest parameters
        differ from the first seen are skipped (and counted)."""
        out: Dict[str, QuantileDigest] = {}
        for _, _, rep in self._rows():
            slo = getattr(rep, "slo", None)
            if slo is None:
                continue
            for name in ("ttft", "gap", "e2e"):
                part = getattr(slo, name).merged()
                have = out.get(name)
                if have is None:
                    out[name] = part
                    continue
                try:
                    have.merge(part)
                except ValueError:
                    self.digest_merge_skipped += 1
        return out

    def goodput(self) -> Dict[str, Any]:
        """Fleet goodput + two-horizon burn over SUMMED window pairs."""
        short_pairs: List[List[int]] = []
        all_pairs: List[List[int]] = []
        cfg = None
        admitted = finished = good = 0
        for _, _, rep in self._rows():
            slo = getattr(rep, "slo", None)
            if slo is None:
                continue
            if cfg is None:
                cfg = slo.config
            wc = slo.window_counts()
            short_pairs.extend(wc["short"])
            all_pairs.extend(wc["all"])
            admitted += slo.admitted_total
            finished += slo.finished_total
            good += slo.good_total
        def _gp(pairs):
            a = sum(p[0] for p in pairs)
            return (sum(p[1] for p in pairs) / a) if a else 1.0
        gp_short, gp_long = _gp(short_pairs), _gp(all_pairs)
        target = cfg.goodput_target if cfg is not None else 0.95
        budget = max(1e-9, 1.0 - target)
        burn_short = max(0.0, 1.0 - gp_short) / budget
        burn_long = max(0.0, 1.0 - gp_long) / budget
        if cfg is not None and burn_short >= cfg.page_burn \
                and burn_long >= cfg.page_burn:
            alert = "page"
        elif cfg is not None and burn_short >= cfg.warn_burn \
                and burn_long >= cfg.warn_burn:
            alert = "warn"
        else:
            alert = "ok"
        return {"goodput_slo": gp_long, "goodput_short": gp_short,
                "burn_short": burn_short, "burn_long": burn_long,
                "alert_state": alert, "admitted": admitted,
                "finished": finished, "good": good}

    def fleet_series(self) -> Dict[str, float]:
        """The derived ``fleet/*`` gauges the exposition carries."""
        r = self.router
        gp = self.goodput()
        out = {
            "fleet/replicas_alive": float(len(r.alive_replicas)),
            "fleet/goodput": gp["goodput_slo"],
            "fleet/burn_short": gp["burn_short"],
            "fleet/burn_long": gp["burn_long"],
            "fleet/alert_level": float(_ALERT_ORDER[gp["alert_state"]]),
        }
        for name, d in self.merged_digests().items():
            if d.count:
                out[f"fleet/{name}_p50_ms"] = d.quantile(0.5)
                out[f"fleet/{name}_p99_ms"] = d.quantile(0.99)
        tl = getattr(r, "transfer_latency", None)
        if tl is not None and tl.count:
            out["fleet/transfer_latency_p50_ms"] = tl.quantile(0.5)
            out["fleet/transfer_latency_p99_ms"] = tl.quantile(0.99)
        js = r.journey_summary()
        out["fleet/journeys_total"] = float(js["total"])
        out["fleet/journeys_finished"] = float(js["finished"])
        out["fleet/journeys_complete"] = float(js["complete"])
        out["fleet/timelines_evicted_open"] = float(sum(
            rep.timelines.evicted_open for _, _, rep in self._rows()))
        return out

    # -- Prometheus exposition -----------------------------------------
    def to_prometheus(self) -> str:
        """One fleet exposition: router series (unlabeled), derived
        ``fleet_*`` gauges, then every replica's series labeled
        ``replica="i",role="..."`` — one ``# TYPE`` line per name,
        samples grouped under it, histograms with merged labels."""
        lines: List[str] = []
        router_text = self.router.registry.to_prometheus()
        if router_text:
            lines.append(router_text.rstrip("\n"))
        series = self.fleet_series()
        for name in sorted(series):
            n = _sanitize(name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {series[name]:g}")
        groups: Dict[str, List] = {}
        for i, role, rep in self._rows():
            labels = f'replica="{i}",role="{role}"'
            for m in rep.registry.metrics():
                groups.setdefault(m.name, []).append((labels, m))
        for name in sorted(groups):
            entries = groups[name]
            kinds = {type(m) for _, m in entries}
            if len(kinds) != 1:
                continue  # type forked across replicas: skip, don't lie
            kind = kinds.pop()
            n = _sanitize(name)
            if kind is Counter:
                lines.append(f"# TYPE {n} counter")
                for labels, m in entries:
                    lines.append(f"{n}{{{labels}}} {m.value:g}")
            elif kind is Gauge:
                lines.append(f"# TYPE {n} gauge")
                for labels, m in entries:
                    lines.append(f"{n}{{{labels}}} {m.value:g}")
            elif kind is Histogram:
                lines.append(f"# TYPE {n} histogram")
                for labels, m in entries:
                    cum = 0
                    for j, b in enumerate(m.buckets):
                        cum += m.counts[j]
                        lines.append(
                            f'{n}_bucket{{{labels},le="{b:g}"}} {cum}')
                    lines.append(
                        f'{n}_bucket{{{labels},le="+Inf"}} {m.count}')
                    lines.append(f"{n}_sum{{{labels}}} {m.total:g}")
                    lines.append(f"{n}_count{{{labels}}} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- /healthz fleet block ------------------------------------------
    def health_summary(self) -> Dict[str, Any]:
        r = self.router
        replicas: Dict[str, Any] = {}
        parked_total = 0
        for i, role, rep in self._rows():
            slo = getattr(rep, "slo", None)
            parked = len(rep.pending_handoffs())
            parked_total += parked
            replicas[str(i)] = {
                "role": role,
                "alert": slo.alert_state if slo is not None else "ok",
                "live": rep.live_count,
                "pending": rep.scheduler.pending,
                "parked_handoffs": parked,
                "open_timelines": len(rep.timelines.open_ids()),
                "step_id": rep.step_id,
            }
        roles: Dict[str, Any] = {}
        for role in ("prefill", "decode", "both"):
            idxs = r._role_indices(role)
            if not idxs:
                continue
            depth = sum(r.replicas[i].scheduler.pending for i in idxs)
            backlog = depth
            if role in ("decode", "both"):
                # pages filled upstream that cannot seat downstream
                backlog += parked_total
            roles[role] = {"replicas": len(idxs), "queue_depth": depth,
                           "backlog": backlog}
        gp = self.goodput()
        return {
            "alert_state": gp["alert_state"],
            "goodput": gp["goodput_slo"],
            "replicas": replicas,
            "dead": [i for i, a in enumerate(r._alive) if not a],
            "roles": roles,
            "journeys": r.journey_summary(),
        }

    # -- bench-facing rollup -------------------------------------------
    def efficiency_snapshot(self) -> Dict[str, Any]:
        r = self.router
        overhead = sum(rep.telemetry_overhead_s
                       for _, _, rep in self._rows())
        overhead += r.journey_overhead_s
        wall = sum(rep.step_wall_s for _, _, rep in self._rows())
        gp = self.goodput()
        out: Dict[str, Any] = {
            "telemetry_overhead_s": overhead,
            "step_wall_s": wall,
            "goodput_slo": gp["goodput_slo"],
            "burn_short": gp["burn_short"],
            "alert_state": gp["alert_state"],
            "journeys": r.journey_summary(),
        }
        if wall:
            out["overhead_pct"] = 100.0 * overhead / wall
        tl = getattr(r, "transfer_latency", None)
        if tl is not None and tl.count:
            out["transfer_latency_p99_ms"] = tl.quantile(0.99)
        for name, d in self.merged_digests().items():
            if d.count:
                out[f"{name}_p99_ms"] = d.quantile(0.99)
        return out

    # -- fleet post-mortems --------------------------------------------
    def post_mortem(self, reason: str, error: Any = None,
                    trigger_replica: Optional[int] = None
                    ) -> Dict[str, Any]:
        """ONE fleet-scoped post-mortem dict: the router's journey and
        scale-event log plus EVERY replica's flight-recorder snapshot
        (dead replicas included — the corpse's ring is exactly the
        evidence), aligned on the shared clock each step record and
        journey hop stamped as ``t``."""
        r = self.router
        replicas: Dict[str, Any] = {}
        for i, rep in enumerate(r.replicas):
            rec = getattr(rep, "recorder", None)
            if rec is not None:
                snap = rec.snapshot(timelines=rep.timelines,
                                    registry=rep.registry,
                                    tracer=rep.tracer)
            else:
                snap = {"steps": [], "records_total": 0,
                        "open_timelines": {}, "registry": {},
                        "last_spans": []}
            snap.update(role=r.roles[i], alive=bool(r._alive[i]),
                        trigger=(i == trigger_replica),
                        step_id=rep.step_id)
            replicas[str(i)] = snap
        return {
            "schema_version": FLEET_SCHEMA_VERSION,
            "reason": reason,
            "error": repr(error) if error is not None else None,
            "time_unix": time.time(),
            "t": r._now(),
            "trigger_replica": trigger_replica,
            "fleet_size": len(r.replicas),
            "roles": list(r.roles),
            "scale_events": list(r.scale_events),
            "journeys": r.recent_journeys(),
            "router": {
                "dispatched": list(r.dispatched),
                "failovers": r.failovers,
                "transfers": r.transfers,
                "transfer_bytes": r.transfer_bytes,
                "registry": r.registry.snapshot(),
            },
            "replicas": replicas,
        }

    def dump(self, reason: str, error: Any = None,
             trigger_replica: Optional[int] = None) -> Optional[str]:
        """Write the fleet post-mortem JSON under ``dump_dir``; returns
        the path, or None without one. Never raises — the caller is
        already unwinding the real failure."""
        if not self.dump_dir:
            return None
        try:
            pm = self.post_mortem(reason, error=error,
                                  trigger_replica=trigger_replica)
            fname = (f"fleet-postmortem-{len(self.dumps):03d}-"
                     f"{reason}.json")
            path = os.path.join(self.dump_dir, fname)
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(pm, f, indent=1, default=_json_default)
        except Exception:
            self.dump_failures += 1
            return None
        self.dumps.append(path)
        return path
