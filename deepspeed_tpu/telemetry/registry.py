"""Process-local metrics registry: counters, gauges, histograms.

``ServingMetrics``, the recompile watchdog, and the wall-clock timers
all publish here; the registry renders as Prometheus text exposition
format (``to_prometheus``) and flushes as ``(tag, value, step)``
monitor events (``publish``) so any configured sink — including the
JSONL sink — receives the same numbers.

Names use the repo's slash convention (``serving/ttft_ms``); the
Prometheus renderer sanitizes them to ``serving_ttft_ms``.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_INVALID = re.compile(r"[^a-zA-Z0-9_:]")

# latency-style default buckets, in ms
DEFAULT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                   500.0, 1000.0, 2000.0, 5000.0)


def _sanitize(name: str) -> str:
    s = _INVALID.sub("_", name)
    if s and s[0].isdigit():
        s = "_" + s
    return s


class Counter:
    """Monotonically increasing value."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        self.value += amount


class Gauge:
    """Point-in-time value; goes up and down."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class Histogram:
    """Fixed-bucket histogram (cumulative, Prometheus-style)."""

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket upper bounds."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, b in enumerate(self.buckets):
            seen += self.counts[i]
            if seen >= target:
                return b
        return self.buckets[-1] if self.buckets else 0.0


class MetricsRegistry:
    """Thread-safe name → metric table with idempotent constructors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # zero-arg callables run before every snapshot/scrape; pull-time
        # sources (tracer ring counters, sink error counts) register one
        # instead of pushing on their own hot paths
        self._collectors: List = []

    def add_collector(self, fn) -> None:
        """Register ``fn()`` to run at the top of every
        :meth:`snapshot` / :meth:`to_prometheus`, typically to copy
        externally-owned counters (tracer drops, sink write errors)
        into gauges. Idempotent per callable object."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                # a broken collector must never take down a scrape
                pass

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets)

    def metrics(self) -> List[object]:
        """Metric objects after running collectors — the raw view the
        fleet aggregator labels per replica instead of re-summing the
        flattened :meth:`snapshot`."""
        self._run_collectors()
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, float]:
        """Flat scalar view (histograms contribute count/sum/p50/p99)."""
        self._run_collectors()
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, (Counter, Gauge)):
                out[m.name] = m.value
            elif isinstance(m, Histogram):
                out[f"{m.name}/count"] = float(m.count)
                out[f"{m.name}/sum"] = m.total
                out[f"{m.name}/p50"] = m.quantile(0.5)
                out[f"{m.name}/p99"] = m.quantile(0.99)
        return out

    def to_prometheus(self) -> str:
        """Render every metric in Prometheus text exposition format."""
        self._run_collectors()
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            name = _sanitize(m.name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {m.value:g}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for i, b in enumerate(m.buckets):
                    cum += m.counts[i]
                    lines.append(f'{name}_bucket{{le="{b:g}"}} {cum}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{name}_sum {m.total:g}")
                lines.append(f"{name}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def publish(self, monitor, step: int) -> int:
        """Flush the scalar snapshot as monitor events; returns count.

        ``monitor`` is any object with the ``MonitorMaster`` interface
        (``enabled`` + ``write_events``); disabled/None monitors are a
        no-op so callers can publish unconditionally.
        """
        if monitor is None or not getattr(monitor, "enabled", False):
            return 0
        events: List[Tuple[str, float, int]] = [
            (f"telemetry/{tag}", value, step)
            for tag, value in sorted(self.snapshot().items())
        ]
        if events:
            monitor.write_events(events)
        return len(events)
