"""Crash flight recorder: a bounded ring of per-step serving records
plus self-contained post-mortem dumps.

``ServingEngine.step`` appends one small dict per step (step id, load
state, queue depth, grants, slot/page occupancy, wall, alert state) —
a deque append, ~zero cost. When the engine is about to raise one of
its fatal conditions (``InvariantViolation``, ``ServingStalledError``,
strict ``RecompileAfterWarmupError``) it asks the recorder for a
post-mortem: the last N step records, every still-open request
timeline, a registry snapshot, the tail of the tracer ring, and the
triggering error — one JSON file that answers "what was the engine
doing when it died" without logs, sinks, or a live process.

``srv.debug_dump()`` returns the same structure live (a /statusz
equivalent); the dump file only adds the reason/error envelope.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# keys every persisted post-mortem carries; pinned by tests so external
# tooling can rely on the file shape
POST_MORTEM_KEYS = ("schema_version", "reason", "error", "time_unix",
                    "steps", "records_total", "open_timelines",
                    "registry", "last_spans", "extra")


def _json_default(obj: Any):
    """Last-resort coercion for numpy scalars and friends."""
    try:
        return float(obj)
    except Exception:
        return str(obj)


class FlightRecorder:
    """Bounded ring of per-step records with post-mortem export."""

    def __init__(self, capacity: int = 256,
                 dump_dir: Optional[str] = None,
                 last_spans: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.last_spans = int(last_spans)
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self.records_total = 0
        self.dumps: List[str] = []          # paths written, in order
        self.dump_failures = 0

    # -- hot path ------------------------------------------------------
    def record(self, rec: Dict[str, Any]) -> None:
        self._ring.append(rec)
        self.records_total += 1

    def last(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        steps = list(self._ring)
        return steps if n is None else steps[-n:]

    @property
    def dump_count(self) -> int:
        return len(self.dumps)

    # -- snapshots -----------------------------------------------------
    def snapshot(self, timelines=None, registry=None,
                 tracer=None) -> Dict[str, Any]:
        """Live statusz view: ring + open timelines + registry + span
        tail. Same payload a post-mortem wraps."""
        open_timelines: Dict[str, Any] = {}
        if timelines is not None:
            try:
                for rid in timelines.open_ids():
                    open_timelines[str(rid)] = timelines.get(rid) or []
            except Exception:
                pass
        spans: List[Dict[str, Any]] = []
        if tracer is not None and getattr(tracer, "enabled", False):
            try:
                spans = tracer.events()[-self.last_spans:]
            except Exception:
                pass
        return {
            "schema_version": SCHEMA_VERSION,
            "steps": self.last(),
            "records_total": self.records_total,
            "open_timelines": open_timelines,
            "registry": registry.snapshot() if registry is not None else {},
            "last_spans": spans,
        }

    def post_mortem(self, reason: str, error: Any = None,
                    timelines=None, registry=None, tracer=None,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        pm = self.snapshot(timelines=timelines, registry=registry,
                           tracer=tracer)
        pm.update(reason=reason,
                  error=repr(error) if error is not None else None,
                  time_unix=time.time(),
                  extra=extra or {})
        return pm

    def dump(self, reason: str, error: Any = None, timelines=None,
             registry=None, tracer=None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write a post-mortem JSON under ``dump_dir``; returns the
        path, or None when no dump_dir is configured. Never raises —
        the caller is already unwinding the real failure."""
        if not self.dump_dir:
            return None
        pm = self.post_mortem(reason, error=error, timelines=timelines,
                              registry=registry, tracer=tracer,
                              extra=extra)
        step = pm["steps"][-1]["step_id"] if pm["steps"] else 0
        fname = (f"postmortem-{len(self.dumps):03d}-step{step}-"
                 f"{reason}.json")
        path = os.path.join(self.dump_dir, fname)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(pm, f, indent=1, default=_json_default)
        except Exception:
            self.dump_failures += 1
            return None
        self.dumps.append(path)
        return path
