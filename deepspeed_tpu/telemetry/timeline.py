"""Per-request lifecycle timelines.

Every :class:`~deepspeed_tpu.serving.request.Request` state transition
is recorded as a timestamped event keyed by request id — submission,
rejection (with reason), admission, each prefill chunk, first token,
speculative accept counts, retirement (with reason), failure, requeue.
The store is host-side and bounded (oldest requests are evicted once
``capacity`` distinct ids have been seen) so it is always on, even
when tracing is off.

When a tracer is attached, each timeline is mirrored as a Chrome
async-nestable track (``ph`` ``b``/``n``/``e``, ``cat="request"``,
``id=request_id``) so per-request lanes render alongside the engine
step spans in Perfetto, and terminal events carry the accumulated
chunk/spec counters.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional


class TimelineStore:
    """Bounded request-id → event-list map, mirrored into a tracer."""

    def __init__(self, capacity: int = 4096, tracer=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.tracer = tracer
        self._lock = threading.Lock()
        # rid -> {"events": [...], "open": bool}
        self._timelines: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()

    def record(self, request_id: int, event: str,
               terminal: bool = False, **attrs) -> None:
        now = time.perf_counter_ns()
        with self._lock:
            tl = self._timelines.get(request_id)
            fresh = tl is None
            if fresh:
                tl = {"events": [], "open": True,
                      "wall_start": time.time()}
                self._timelines[request_id] = tl
                while len(self._timelines) > self.capacity:
                    self._timelines.popitem(last=False)
            tl["events"].append(
                {"event": event, "t_ns": now, "attrs": attrs or None})
            was_open = tl["open"]
            if terminal:
                tl["open"] = False
        tr = self.tracer
        if tr is not None and tr.enabled:
            track = f"req-{request_id}"
            if fresh:
                tr.async_begin("request", track, request_id, event=event,
                               **attrs)
            if not fresh or attrs:
                tr.async_instant("request", event, request_id, **attrs)
            if terminal and was_open:
                tr.async_end("request", track, request_id, event=event,
                             **attrs)

    def get(self, request_id: int) -> Optional[List[Dict[str, Any]]]:
        """Events for one request, oldest first, or None if evicted/unknown."""
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is None:
                return None
            return list(tl["events"])

    def events_of(self, request_id: int) -> List[str]:
        """Just the event names, for terse assertions."""
        tl = self.get(request_id)
        return [e["event"] for e in tl] if tl else []

    def open_ids(self) -> List[int]:
        """Request ids that never saw a terminal event — the timeline
        COMPLETENESS check the chaos harness asserts against: after a
        drain, every submitted request must have ended in a terminal
        event (finished/rejected/failed), so this must be empty. A
        non-empty result names the requests whose lifecycle was dropped
        on the floor."""
        with self._lock:
            return [rid for rid, tl in self._timelines.items()
                    if tl["open"]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._timelines)
