"""Per-request lifecycle timelines.

Every :class:`~deepspeed_tpu.serving.request.Request` state transition
is recorded as a timestamped event keyed by request id — submission,
rejection (with reason), admission, each prefill chunk, first token,
speculative accept counts, retirement (with reason), failure, requeue.
The store is host-side and bounded (oldest requests are evicted once
``capacity`` distinct ids have been seen) so it is always on, even
when tracing is off.

When a tracer is attached, each timeline is mirrored as a Chrome
async-nestable track (``ph`` ``b``/``n``/``e``, ``cat="request"``,
``id=request_id``) so per-request lanes render alongside the engine
step spans in Perfetto, and terminal events carry the accumulated
chunk/spec counters.

Fleet extensions: a store owned by a fleet replica carries a
``replica_id`` that is stamped onto every event's attrs, so the
router's journey stitcher can merge timelines from several stores and
still attribute each hop. ``record(..., parked=True)`` flags a request
that is parked mid-handoff (prefill done, pages not yet adopted by a
decode home) — :meth:`parked_ids` exposes those so the completeness
probe does not mistake "closed on the prefill side" for "done".
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional


class TimelineStore:
    """Bounded request-id → event-list map, mirrored into a tracer."""

    def __init__(self, capacity: int = 4096, tracer=None,
                 replica_id: Optional[int] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.tracer = tracer
        self.replica_id = replica_id
        self._lock = threading.Lock()
        # rid -> {"events": [...], "open": bool, "parked": bool}
        self._timelines: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        # open timelines pushed out by the ring before their terminal
        # event — the one way a request can go silently "complete"
        self.evicted_open = 0

    def record(self, request_id: int, event: str,
               terminal: bool = False, parked: bool = False,
               **attrs) -> None:
        now = time.perf_counter_ns()
        if self.replica_id is not None:
            attrs.setdefault("replica", self.replica_id)
        with self._lock:
            tl = self._timelines.get(request_id)
            fresh = tl is None
            if fresh:
                tl = {"events": [], "open": True, "parked": False,
                      "wall_start": time.time()}
                self._timelines[request_id] = tl
                while len(self._timelines) > self.capacity:
                    _, old = self._timelines.popitem(last=False)
                    if old["open"]:
                        self.evicted_open += 1
            tl["events"].append(
                {"event": event, "t_ns": now, "attrs": attrs or None})
            was_open = tl["open"]
            tl["parked"] = parked
            if terminal:
                tl["open"] = False
        tr = self.tracer
        if tr is not None and tr.enabled:
            track = f"req-{request_id}"
            if fresh:
                tr.async_begin("request", track, request_id, event=event,
                               **attrs)
            if not fresh or attrs:
                tr.async_instant("request", event, request_id, **attrs)
            if terminal and was_open:
                tr.async_end("request", track, request_id, event=event,
                             **attrs)

    def get(self, request_id: int) -> Optional[List[Dict[str, Any]]]:
        """Events for one request, oldest first, or None if evicted/unknown."""
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is None:
                return None
            return list(tl["events"])

    def events_of(self, request_id: int) -> List[str]:
        """Just the event names, for terse assertions."""
        tl = self.get(request_id)
        return [e["event"] for e in tl] if tl else []

    def open_ids(self) -> List[int]:
        """Request ids that never saw a terminal event — the timeline
        COMPLETENESS check the chaos harness asserts against: after a
        drain, every submitted request must have ended in a terminal
        event (finished/rejected/failed), so this must be empty. A
        non-empty result names the requests whose lifecycle was dropped
        on the floor."""
        with self._lock:
            return [rid for rid, tl in self._timelines.items()
                    if tl["open"]]

    def parked_ids(self) -> List[int]:
        """Request ids whose LAST event parked them mid-handoff.

        A prefill-side timeline ends with a terminal ``handed_off``
        only once a decode home adopts the pages; until then the
        request sits in ``pending_handoffs`` with its timeline marked
        parked. The fleet completeness probe treats parked ∪ open as
        "not done" — a request stranded between homes must not count
        as complete on either."""
        with self._lock:
            return [rid for rid, tl in self._timelines.items()
                    if tl.get("parked")]

    def is_open(self, request_id: int) -> Optional[bool]:
        """True/False for a known request id, None if evicted/unknown."""
        with self._lock:
            tl = self._timelines.get(request_id)
            return None if tl is None else bool(tl["open"])

    def __len__(self) -> int:
        with self._lock:
            return len(self._timelines)
