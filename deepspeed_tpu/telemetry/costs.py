"""XLA program cost model: flops/bytes per executable, live MFU and
bandwidth-utilization gauges, and KV-HBM reconciliation.

DeepSpeed ships a flops profiler that walks modules and counts MACs;
on JAX the compiler already knows — ``lowered.compile()`` exposes
``cost_analysis()`` (flops, bytes accessed) and ``memory_analysis()``
(argument/output/temp bytes) for every executable. This module
harvests those numbers once per ``(program, signature)`` through the
PR-5 ``_WatchedJit`` seam and charges them to the serving step loop on
every call, which turns wall-clock spans into hardware-relative
efficiency:

* ``MFU``            = flops executed / wall / device peak flops
* ``bandwidth_util`` = bytes accessed / wall / device peak HBM BW
* ``tokens_per_gflop`` = emitted tokens / (flops / 1e9)

Harvesting is best-effort: ``cost_analysis`` coverage varies by
backend (PJRT plugins may return nothing), so failures record a
``telemetry/cost_model_unavailable`` gauge and the affected program
simply contributes zero — the serving loop itself is never perturbed
(a CPU test pins bit-identical outputs with the model on vs off).

The AOT harvest compiles the (already warm) program out-of-band, so it
runs under :func:`~.watchdog.suppress_compile_events` to stay invisible
to the recompile watchdog, and lowers against ``ShapeDtypeStruct``
avals so donated buffers are never touched.

KV-HBM reconciliation: :func:`kv_hbm_report` computes the
model-predicted KV footprint from ``KVCacheSpec`` math (paged:
``num_pages x page_bytes``; contiguous: ``num_slots x max_seq_len``
rows) and diffs it against the pool's actual device array bytes plus
``get_accelerator().memory_stats()``. Drift beyond tolerance emits a
``telemetry/hbm_drift`` monitor event — the canary for a pool layout
change silently inflating the cache.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .watchdog import fast_key, suppress_compile_events

try:  # pragma: no cover - jax is always present in this repo
    import jax
except Exception:  # pragma: no cover
    jax = None

# (peak_flops, peak_bytes_per_s) by device-kind substring, first match
# wins. Dense bf16 peaks; HBM bandwidth from public TPU system specs.
_DEVICE_PEAKS: Tuple[Tuple[str, Tuple[float, float]], ...] = (
    ("v6e", (918e12, 1640e9)),
    ("v5p", (459e12, 2765e9)),
    ("v5e", (197e12, 819e9)),
    ("v5lite", (197e12, 819e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (46e12, 700e9)),
)
# CPU (and unknown devices) get nominal figures so MFU stays a nonzero,
# host-comparable ratio; gates on it are warn-only off-TPU.
_NOMINAL_PEAKS = (1e12, 1e11)

_MISSING = object()


def _mesh_device_count() -> int:
    """Devices participating in the active global mesh (1 when no mesh
    is set — the single-chip default)."""
    try:
        from ..parallel import mesh as mesh_mod
        if mesh_mod.has_mesh():
            return int(mesh_mod.get_mesh().devices.size)
    except Exception:
        pass
    return 1


def resolve_peaks(device=None) -> Tuple[float, float]:
    """(peak_flops, peak_bytes_per_s) for the first local device."""
    kind = ""
    try:
        dev = device if device is not None else jax.devices()[0]
        kind = str(getattr(dev, "device_kind", "")).lower()
    except Exception:
        pass
    for key, peaks in _DEVICE_PEAKS:
        if key in kind:
            return peaks
    return _NOMINAL_PEAKS


def _abstract(x: Any) -> Any:
    """Array → ShapeDtypeStruct (sharding-preserving when possible) so
    lowering for harvest never reads — or resurrects — real buffers."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None or jax is None:
        return x
    sharding = getattr(x, "sharding", None)
    if sharding is not None:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        except Exception:
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _abstract_plain(x: Any) -> Any:
    """Placement-free twin of :func:`_abstract`: shape/dtype only. The
    live dispatch lets jit place uncommitted (host-staged) inputs next
    to committed params, but sharding-preserving avals freeze that mix
    into an inconsistent placement AOT lowering rejects — stripping
    placement entirely lowers the same program for costing purposes."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None or jax is None:
        return x
    return jax.ShapeDtypeStruct(shape, dtype)


class ProgramCostModel:
    """Per-``(program, fast signature)`` flops/bytes registry with
    running totals and per-step window gauges.

    Subscribed to ``_WatchedJit`` proxies (via
    ``RecompileWatchdog.attach``); every proxied call lands in
    :meth:`account`, which lazily harvests unknown signatures — so a
    model attached to already-warm programs still gets costed on first
    use, paying one suppressed AOT compile per signature.
    """

    def __init__(self, registry=None, peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None,
                 hbm_tolerance: float = 0.01, kv_every: int = 16,
                 num_devices: Optional[int] = None):
        pf, pb = resolve_peaks()
        # normalize utilization by the mesh, not one chip: cost_analysis
        # reports WHOLE-program flops/bytes, so on a sharded mesh the
        # denominator is nominal-peak × participating devices — a TP=4
        # run reporting single-chip MFU > 1.0 was the bug this fixes.
        # Explicit peak_flops/peak_bytes_per_s overrides are taken as
        # ALREADY aggregate (callers passing a measured system peak).
        if num_devices is None:
            num_devices = _mesh_device_count()
        self.num_devices = max(1, int(num_devices))
        self.peak_flops = (float(peak_flops) if peak_flops
                           else pf * self.num_devices)
        self.peak_bytes_per_s = (float(peak_bytes_per_s)
                                 if peak_bytes_per_s
                                 else pb * self.num_devices)
        self.hbm_tolerance = float(hbm_tolerance)
        # KV reconciliation cadence in steps (drift is a slow leak, not
        # a per-step event; pull paths always reconcile fresh)
        self.kv_every = max(1, int(kv_every))
        self.registry = registry
        self._handles: Optional[Tuple[Any, ...]] = None  # cached metrics
        # (program, fast key) -> cost dict, or None when harvest failed
        self.programs: Dict[Tuple[str, Any], Optional[Dict[str, float]]] = {}
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.calls_total = 0
        self.uncosted_calls = 0
        self.harvests = 0
        self.unavailable = 0
        self.wall_total_s = 0.0
        self.tokens_total = 0
        # instrumentation self-accounting (the <=3% overhead budget);
        # one-time harvest compiles are tracked separately from the
        # steady-state per-call cost
        self.overhead_ns = 0
        self.harvest_ns = 0
        # window (since last step_update) accumulators and live gauges
        self._win_flops = 0.0
        self._win_bytes = 0.0
        self.mfu = 0.0
        self.bandwidth_util = 0.0
        self.tokens_per_gflop = 0.0
        self.hbm: Dict[str, float] = {}
        self._hbm_drifted = False

    # -- per-call accounting (hot path) --------------------------------
    def account(self, program: str, fn, args, kwargs) -> None:
        t0 = time.perf_counter_ns()
        key = (program, fast_key(args, kwargs))
        cost = self.programs.get(key, _MISSING)
        if cost is _MISSING:
            self.overhead_ns += time.perf_counter_ns() - t0
            cost = self._harvest(key, fn, args, kwargs)
            t0 = time.perf_counter_ns()
        self.calls_total += 1
        if cost is not None:
            self._win_flops += cost["flops"]
            self._win_bytes += cost["bytes"]
        else:
            self.uncosted_calls += 1
        self.overhead_ns += time.perf_counter_ns() - t0

    # -- harvest (cold path, once per signature) -----------------------
    def _harvest(self, key, fn, args, kwargs) -> Optional[Dict[str, float]]:
        t0 = time.perf_counter_ns()
        cost: Optional[Dict[str, float]] = None
        try:
            aargs, akwargs = jax.tree_util.tree_map(_abstract,
                                                    (args, kwargs))
            with suppress_compile_events():
                try:
                    compiled = fn.lower(*aargs, **akwargs).compile()
                except Exception:
                    # mixed committed/uncommitted inputs (replicated
                    # params + a host-staged token pinned to one device)
                    # lower fine live but not as frozen avals; retry
                    # with placement stripped
                    aargs, akwargs = jax.tree_util.tree_map(
                        _abstract_plain, (args, kwargs))
                    compiled = fn.lower(*aargs, **akwargs).compile()
                ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            ca = dict(ca or {})
            cost = {"flops": max(0.0, float(ca.get("flops", 0.0))),
                    "bytes": max(0.0, float(ca.get("bytes accessed", 0.0)))}
            try:
                ma = compiled.memory_analysis()
                arg_b = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
                out_b = float(getattr(ma, "output_size_in_bytes", 0) or 0)
                tmp_b = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
                peak = getattr(ma, "peak_memory_in_bytes", None)
                if peak is None:
                    # CPU backend reports no peak; arg+out+temp is the
                    # standard upper-bound proxy
                    peak = arg_b + out_b + tmp_b
                cost.update(arg_bytes=arg_b, output_bytes=out_b,
                            temp_bytes=tmp_b, peak_bytes=float(peak))
            except Exception:
                pass
            self.harvests += 1
        except Exception:
            # best-effort across backends: some PJRT plugins implement
            # neither AOT lowering nor cost_analysis for every program
            cost = None
            self.unavailable += 1
            if self.registry is not None:
                self.registry.gauge(
                    "telemetry/cost_model_unavailable").set(self.unavailable)
        self.programs[key] = cost
        self.harvest_ns += time.perf_counter_ns() - t0
        return cost

    # -- per-step gauges -----------------------------------------------
    def step_update(self, wall_s: float, tokens: int = 0,
                    tracer=None) -> None:
        """Fold the window's flops/bytes into gauges against ``wall_s``
        (the step's span duration). Called once per serving step."""
        f, b = self._win_flops, self._win_bytes
        self._win_flops = 0.0
        self._win_bytes = 0.0
        self.wall_total_s += wall_s
        self.tokens_total += int(tokens)
        self.flops_total += f
        self.bytes_total += b
        if wall_s > 0:
            self.mfu = f / wall_s / self.peak_flops
            self.bandwidth_util = b / wall_s / self.peak_bytes_per_s
        self.tokens_per_gflop = tokens / (f / 1e9) if f > 0 else 0.0
        if self.registry is not None:
            if self._handles is None:
                # resolve the metric objects once: registry lookups take
                # a lock each, too dear for 5 of them per serving step
                g, c = self.registry.gauge, self.registry.counter
                self._handles = (g("telemetry/mfu"),
                                 g("telemetry/bandwidth_util"),
                                 g("telemetry/tokens_per_gflop"),
                                 c("telemetry/flops_total"),
                                 c("telemetry/bytes_accessed_total"))
            h = self._handles
            h[0].set(self.mfu)
            h[1].set(self.bandwidth_util)
            h[2].set(self.tokens_per_gflop)
            h[3].inc(f)
            h[4].inc(b)
        if tracer is not None:
            tracer.counter("telemetry/efficiency", mfu=self.mfu,
                           bandwidth_util=self.bandwidth_util)

    # -- KV HBM reconciliation -----------------------------------------
    def reconcile_kv(self, pool, monitor=None, step: int = 0,
                     tracer=None) -> Dict[str, float]:
        """Diff model-predicted KV bytes against the pool's device
        arrays (+ accelerator memory stats); emit ``telemetry/hbm_drift``
        on a tolerance-crossing transition. The serving loop calls this
        every ``kv_every`` steps; pull paths (``efficiency_snapshot``)
        call it directly for a fresh reading."""
        rep = kv_hbm_report(pool)
        rep.update(device_memory_report())
        if not rep.get("hbm_peak_bytes"):
            # CPU runtimes report no allocator stats; the KV pool is the
            # allocation this layer tracks, so fall back to its size
            rep["hbm_peak_bytes"] = rep["kv_bytes_actual"]
        drifted = rep["hbm_drift"] > self.hbm_tolerance
        if self.registry is not None:
            g = self.registry.gauge
            g("telemetry/kv_bytes_predicted").set(rep["kv_bytes_predicted"])
            g("telemetry/kv_bytes_actual").set(rep["kv_bytes_actual"])
            g("telemetry/hbm_drift").set(rep["hbm_drift"])
            g("telemetry/hbm_peak_bytes").set(rep["hbm_peak_bytes"])
        if drifted and not self._hbm_drifted:
            if tracer is not None:
                tracer.instant("telemetry/hbm_drift", **rep)
            if monitor is not None and getattr(monitor, "enabled", False):
                monitor.write_events([
                    ("telemetry/hbm_drift", rep["hbm_drift"], int(step))])
        self._hbm_drifted = drifted
        self.hbm = rep
        return rep

    # -- lifecycle -----------------------------------------------------
    def reset_totals(self) -> None:
        """Zero the running totals (keep harvested program costs) so a
        bench can measure a clean window after warmup."""
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.calls_total = 0
        self.uncosted_calls = 0
        self.wall_total_s = 0.0
        self.tokens_total = 0
        self.overhead_ns = 0
        self._win_flops = 0.0
        self._win_bytes = 0.0

    @property
    def overhead_s(self) -> float:
        return self.overhead_ns / 1e9

    def summary(self) -> Dict[str, Any]:
        wall = self.wall_total_s
        flops, byts = self.flops_total, self.bytes_total
        return {
            "programs": len(self.programs),
            "harvests": self.harvests,
            "unavailable": self.unavailable,
            "calls_total": self.calls_total,
            "uncosted_calls": self.uncosted_calls,
            "flops_total": flops,
            "bytes_accessed_total": byts,
            "tokens_total": self.tokens_total,
            "wall_s": wall,
            "mfu": flops / wall / self.peak_flops if wall > 0 else 0.0,
            "bandwidth_util": (byts / wall / self.peak_bytes_per_s
                               if wall > 0 else 0.0),
            "tokens_per_gflop": (self.tokens_total / (flops / 1e9)
                                 if flops > 0 else 0.0),
            "peak_flops": self.peak_flops,
            "peak_bytes_per_s": self.peak_bytes_per_s,
            "num_devices": self.num_devices,
            "overhead_s": self.overhead_s,
            "harvest_s": self.harvest_ns / 1e9,
            "hbm": dict(self.hbm),
        }


# ----------------------------------------------------------------------
# KV HBM math
# ----------------------------------------------------------------------
_KV_LEAVES = ("k", "v", "k_scale", "v_scale")


def kv_hbm_report(pool) -> Dict[str, float]:
    """Predicted vs actual KV-cache bytes for a Slot/PagedKV pool.

    Predicted comes from ``KVCacheSpec`` math alone (never from array
    shapes): per-token bytes x capacity tokens, where capacity is
    ``num_pages x page_size`` for the paged pool and
    ``num_slots x max_seq_len`` for contiguous rows. Actual sums
    ``.nbytes`` over the pool's k/v (+ scale) device leaves — the
    ``index``/``table`` bookkeeping arrays are not KV storage and are
    excluded from both sides, so a healthy pool reports drift 0.0.
    """
    spec = pool.spec
    item = np.dtype(spec.dtype).itemsize
    per_token = spec.n_layer * spec.kv_heads * spec.cache_d * 2 * item
    if spec.quantized:
        per_token += spec.n_layer * spec.kv_heads * 2 * 4  # f32 scales
    paged = hasattr(pool, "num_pages")
    if paged:
        tokens = pool.num_pages * pool.page_size
        page_bytes = per_token * pool.page_size
    else:
        tokens = pool.num_slots * spec.max_seq_len
        page_bytes = 0.0
    predicted = float(per_token * tokens)
    cs = pool.cache.get("cache_store", {})
    actual = 0.0
    for leaf_name in _KV_LEAVES:
        leaf = cs.get(leaf_name)
        if leaf is not None:
            actual += float(leaf.nbytes)
    drift = abs(actual - predicted) / predicted if predicted > 0 else 0.0
    rep = {
        "kv_bytes_predicted": predicted,
        "kv_bytes_actual": actual,
        "kv_bytes_per_token": float(per_token),
        "kv_capacity_tokens": float(tokens),
        "hbm_drift": drift,
        "layout": "paged" if paged else "contiguous",
    }
    if paged:
        rep["pages_total"] = float(pool.num_pages)
        rep["page_bytes"] = float(page_bytes)
    return rep


def device_memory_report() -> Dict[str, float]:
    """Accelerator allocator stats (empty dict values → 0 on CPU)."""
    stats: Dict[str, Any] = {}
    try:
        from ..accelerator import get_accelerator
        stats = get_accelerator().memory_stats() or {}
    except Exception:
        pass
    return {
        "hbm_bytes_in_use": float(stats.get("bytes_in_use", 0) or 0),
        "hbm_peak_bytes": float(stats.get("peak_bytes_in_use", 0) or 0),
        "hbm_bytes_limit": float(stats.get("bytes_limit", 0) or 0),
    }
