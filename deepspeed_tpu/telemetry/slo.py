"""Streaming SLO / goodput accounting: O(1)-memory quantile digests,
per-window goodput counters, and multi-window burn-rate alerting.

ROADMAP item 2 wants the async front end benched on *goodput under
SLO*, not raw throughput. The sensors for that live here:

* :class:`QuantileDigest` — an HDR-histogram-style log-bucketed
  estimator: fixed memory, bounded *relative* error (midpoint of a
  geometric bucket is within ``rel_error`` of any value in it), and
  mergeable by adding bucket counts. p50/p90/p99 therefore come from a
  stream without retaining samples — unlike the post-hoc numpy
  percentiles ``ServingMetrics.snapshot`` computes from full lists.
* :class:`WindowedQuantiles` — a ring of K sub-digests; the serving
  loop rotates every ``window_steps`` steps, so quantiles reflect a
  sliding window, not process lifetime.
* :class:`SLOTracker` — judges each finished request against
  :class:`SLOConfig` targets (TTFT / inter-token gap / e2e, per
  priority class), maintains per-window goodput (requests finished
  within SLO ÷ admitted), and derives SRE-style multi-window burn
  rates: ``burn = (1 - goodput) / (1 - goodput_target)`` over a short
  (last 2 windows) and long (all windows) horizon. Alert state is
  ``page`` when both horizons burn ≥ ``page_burn``, ``warn`` when both
  ≥ ``warn_burn``, else ``ok`` — requiring both horizons suppresses
  one-window blips while still paging fast on sustained burn. The same
  two-horizon formula runs PER PRIORITY CLASS over per-class window
  rings (``class_alert``) — that is the signal the serving front end's
  burn-rate-driven shedding/preemption consumes.

Everything exports through the existing sinks: registry gauges (hence
Prometheus), Perfetto counter tracks, and monitor events on alert
transitions.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Dict, List, Optional

_ALERT_LEVELS = {"ok": 0, "warn": 1, "page": 2}


class QuantileDigest:
    """Log-bucketed streaming quantile estimator (HDR-histogram style).

    Values are assigned to geometric buckets growing by
    ``1 + 2 * rel_error``; a quantile is answered as the geometric
    midpoint of the bucket holding that rank, clamped to the observed
    min/max — so the estimate's relative error is bounded by
    ``rel_error`` regardless of the distribution's shape. Memory is a
    fixed ``O(log(max/min) / rel_error)`` int array; merging two
    digests with identical parameters is elementwise addition.
    """

    __slots__ = ("min_value", "max_value", "rel_error", "_log_growth",
                 "_growth", "_nbuckets", "counts", "count", "_vmin",
                 "_vmax")

    def __init__(self, min_value: float = 1e-2, max_value: float = 1e7,
                 rel_error: float = 0.01):
        if not (0 < min_value < max_value):
            raise ValueError(f"need 0 < min_value < max_value, got "
                             f"{min_value}, {max_value}")
        if not (0 < rel_error < 0.5):
            raise ValueError(f"rel_error must be in (0, 0.5), got "
                             f"{rel_error}")
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self.rel_error = float(rel_error)
        self._growth = 1.0 + 2.0 * rel_error
        self._log_growth = math.log(self._growth)
        self._nbuckets = int(math.ceil(
            math.log(max_value / min_value) / self._log_growth)) + 1
        self.counts = [0] * self._nbuckets
        self.count = 0
        self._vmin = math.inf
        self._vmax = 0.0

    def _bucket(self, v: float) -> int:
        if v <= self.min_value:
            return 0
        i = int(math.log(v / self.min_value) / self._log_growth)
        return i if i < self._nbuckets else self._nbuckets - 1

    def add(self, value: float, n: int = 1) -> None:
        v = float(value)
        if math.isnan(v):
            return
        if v < 0.0:
            v = 0.0
        self.counts[self._bucket(v)] += n
        self.count += n
        if v < self._vmin:
            self._vmin = v
        if v > self._vmax:
            self._vmax = v

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                est = self.min_value * self._growth ** (i + 0.5)
                return min(max(est, self._vmin), self._vmax)
        return self._vmax

    def merge(self, other: "QuantileDigest") -> "QuantileDigest":
        if (other.min_value, other.max_value, other.rel_error) != \
                (self.min_value, self.max_value, self.rel_error):
            raise ValueError("cannot merge digests with different "
                             "bucket parameters")
        for i, c in enumerate(other.counts):
            if c:
                self.counts[i] += c
        self.count += other.count
        self._vmin = min(self._vmin, other._vmin)
        self._vmax = max(self._vmax, other._vmax)
        return self

    def clear(self) -> None:
        for i in range(self._nbuckets):
            self.counts[i] = 0
        self.count = 0
        self._vmin = math.inf
        self._vmax = 0.0


class WindowedQuantiles:
    """Ring of ``windows`` sub-digests; :meth:`rotate` seals the
    current window and recycles the oldest, so :meth:`quantile`
    (computed over the merged ring) is a sliding-window view."""

    def __init__(self, windows: int = 8, **digest_kw):
        if windows < 1:
            raise ValueError(f"windows must be >= 1, got {windows}")
        self._kw = dict(digest_kw)
        self._ring: List[QuantileDigest] = [
            QuantileDigest(**self._kw) for _ in range(windows)]
        self._cur = 0

    @property
    def windows(self) -> int:
        return len(self._ring)

    @property
    def count(self) -> int:
        return sum(d.count for d in self._ring)

    def add(self, value: float, n: int = 1) -> None:
        self._ring[self._cur].add(value, n)

    def rotate(self) -> None:
        self._cur = (self._cur + 1) % len(self._ring)
        self._ring[self._cur].clear()

    def merged(self) -> QuantileDigest:
        out = QuantileDigest(**self._kw)
        for d in self._ring:
            if d.count:
                out.merge(d)
        return out

    def quantile(self, q: float) -> float:
        return self.merged().quantile(q)


@dataclasses.dataclass
class SLOTargets:
    """Latency targets for one priority class, in milliseconds.
    ``None`` disables that criterion."""
    ttft_ms: Optional[float] = 500.0
    gap_ms: Optional[float] = 200.0     # mean inter-token gap
    e2e_ms: Optional[float] = None


def _targets_from(value: Any) -> SLOTargets:
    if isinstance(value, SLOTargets):
        return value
    return SLOTargets(**dict(value or {}))


@dataclasses.dataclass
class SLOConfig:
    """SLO targets per priority class plus windowing/alert policy.

    ``resolve`` accepts the serving-knob forms: ``True`` (defaults), an
    ``SLOConfig``, or a dict — top-level ``ttft_ms``/``gap_ms``/
    ``e2e_ms`` keys configure the ``default`` class, a ``classes`` dict
    adds per-priority targets, and the remaining keys map to config
    fields."""

    classes: Dict[str, SLOTargets] = dataclasses.field(
        default_factory=lambda: {"default": SLOTargets()})
    goodput_target: float = 0.95       # SLO objective; error budget base
    warn_burn: float = 2.0
    page_burn: float = 10.0
    window_steps: int = 128            # serving steps per digest window
    windows: int = 8
    digest_rel_error: float = 0.01

    @classmethod
    def resolve(cls, value: Any) -> Optional["SLOConfig"]:
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            kw = dict(value)
            default = {k: kw.pop(k) for k in ("ttft_ms", "gap_ms", "e2e_ms")
                       if k in kw}
            classes = {name: _targets_from(t)
                       for name, t in kw.pop("classes", {}).items()}
            if default or "default" not in classes:
                base = classes.get("default", SLOTargets())
                classes["default"] = dataclasses.replace(base, **default)
            return cls(classes=classes, **kw)
        raise TypeError(f"cannot resolve SLOConfig from {value!r}")


class SLOTracker:
    """Judges request completions against SLO targets and maintains
    windowed goodput + burn-rate alert state. Fed by the serving loop:
    ``observe_admitted`` on accepted submission, ``observe_gap`` per
    decode step, ``observe_finish`` per completed request, ``on_step``
    once per step (rotation + export)."""

    def __init__(self, config: Any = True, registry=None, tracer=None,
                 monitor=None):
        self.config = SLOConfig.resolve(config) or SLOConfig()
        cfg = self.config
        dk = dict(min_value=1e-2, max_value=1e7,
                  rel_error=cfg.digest_rel_error)
        self.ttft = WindowedQuantiles(cfg.windows, **dk)
        self.gap = WindowedQuantiles(cfg.windows, **dk)
        self.e2e = WindowedQuantiles(cfg.windows, **dk)
        self.registry = registry
        self.tracer = tracer
        self.monitor = monitor
        # per-window [admitted, finished-within-SLO] counters
        self._gw: List[List[int]] = [[0, 0] for _ in range(cfg.windows)]
        self._gw_cur = 0
        self.admitted_total = 0
        self.finished_total = 0
        self.good_total = 0
        self.per_class: Dict[str, List[int]] = {}
        # per-class windowed counters, same ring layout as _gw, created
        # lazily per class — these drive the PER-CLASS burn rates the
        # priority scheduler's shedding/preemption loop consumes
        self._cw: Dict[str, List[List[int]]] = {}
        self.class_alerts: Dict[str, str] = {}
        self.class_burns: Dict[str, List[float]] = {}  # cls -> [short, long]
        self.cancelled_total = 0
        self.alert_state = "ok"
        self.burn_short = 0.0
        self.burn_long = 0.0
        self.rotations = 0
        self._steps_in_window = 0
        self._p99_cache: Dict[str, float] = {}
        self.overhead_ns = 0
        self._handles = None            # cached registry metric objects

    # -- feeds ---------------------------------------------------------
    def _class_targets(self, cls: str) -> SLOTargets:
        return self.config.classes.get(cls) \
            or self.config.classes.get("default") or SLOTargets()

    def _class_window(self, cls: str) -> List[List[int]]:
        cw = self._cw.get(cls)
        if cw is None:
            cw = self._cw[cls] = [[0, 0]
                                  for _ in range(self.config.windows)]
        return cw

    def observe_admitted(self, cls: str = "default") -> None:
        self.admitted_total += 1
        self._gw[self._gw_cur][0] += 1
        self.per_class.setdefault(cls, [0, 0, 0])[0] += 1
        self._class_window(cls)[self._gw_cur][0] += 1

    def observe_cancel(self, cls: str = "default") -> None:
        """Un-admit a cancelled request: client cancellation (or
        disconnect) is neither good nor bad service, so it must not
        move goodput either way. The admitted counters are decremented
        where the admission still sits; if the admitting window has
        already rotated out, the decrement lands in the current window
        instead — a bounded, self-correcting artifact (each such cancel
        offsets at most one admission of the same class, and windows
        are short relative to request lifetimes)."""
        self.cancelled_total += 1
        self._gw[self._gw_cur][0] = max(0, self._gw[self._gw_cur][0] - 1)
        if self.admitted_total > 0:
            self.admitted_total -= 1
        pc = self.per_class.setdefault(cls, [0, 0, 0])
        pc[0] = max(0, pc[0] - 1)
        cw = self._class_window(cls)[self._gw_cur]
        cw[0] = max(0, cw[0] - 1)

    def observe_gap(self, gap_s: float) -> None:
        t0 = time.perf_counter_ns()
        self.gap.add(gap_s * 1e3)
        self.overhead_ns += time.perf_counter_ns() - t0

    def observe_finish(self, ttft_s: Optional[float] = None,
                       per_token_s: Optional[float] = None,
                       e2e_s: Optional[float] = None,
                       cls: str = "default", ok: bool = True) -> bool:
        """Record a completed request; returns whether it met its SLO.
        ``ok=False`` (deadline expiry, failure) makes the request count
        against goodput regardless of its latencies — a fast failure is
        not good service."""
        t0 = time.perf_counter_ns()
        t = self._class_targets(cls)
        within = bool(ok)
        if ttft_s is not None:
            self.ttft.add(ttft_s * 1e3)
        if t.ttft_ms is not None:
            within = within and (ttft_s is not None
                                 and ttft_s * 1e3 <= t.ttft_ms)
        if e2e_s is not None:
            self.e2e.add(e2e_s * 1e3)
        if t.e2e_ms is not None:
            within = within and (e2e_s is not None
                                 and e2e_s * 1e3 <= t.e2e_ms)
        if t.gap_ms is not None and per_token_s is not None:
            within = within and per_token_s * 1e3 <= t.gap_ms
        self.finished_total += 1
        pc = self.per_class.setdefault(cls, [0, 0, 0])
        pc[1] += 1
        if within:
            self.good_total += 1
            self._gw[self._gw_cur][1] += 1
            pc[2] += 1
            self._class_window(cls)[self._gw_cur][1] += 1
        else:
            self._class_window(cls)  # materialize the ring so the class
            #                          shows up in burn/alert maps
        self.overhead_ns += time.perf_counter_ns() - t0
        return within

    # -- derived state -------------------------------------------------
    @staticmethod
    def _goodput_of(pairs) -> float:
        admitted = sum(p[0] for p in pairs)
        good = sum(p[1] for p in pairs)
        return good / admitted if admitted else 1.0

    def goodput(self) -> float:
        """Sliding-window goodput: finished-within-SLO ÷ admitted."""
        return self._goodput_of(self._gw)

    def window_counts(self) -> Dict[str, List[List[int]]]:
        """Raw ``[admitted, good]`` window pairs for fleet merging.

        ``short`` is the two-window burn horizon (current + previous),
        ``all`` the full ring. A fleet aggregator sums the pairs
        ACROSS replicas and runs the same :meth:`_burn` formula on the
        merged counts — mathematically identical to one tracker having
        observed every request, which averaging per-replica burn rates
        is not (replicas with 2 requests would weigh as much as ones
        with 2000)."""
        prev_i = (self._gw_cur - 1) % self.config.windows
        return {
            "short": [list(self._gw[self._gw_cur]), list(self._gw[prev_i])],
            "all": [list(p) for p in self._gw],
        }

    def _burn(self, goodput: float) -> float:
        budget = max(1e-9, 1.0 - self.config.goodput_target)
        return max(0.0, 1.0 - goodput) / budget

    def _alert_of(self, burn_short: float, burn_long: float) -> str:
        cfg = self.config
        if burn_short >= cfg.page_burn and burn_long >= cfg.page_burn:
            return "page"
        if burn_short >= cfg.warn_burn and burn_long >= cfg.warn_burn:
            return "warn"
        return "ok"

    def _recompute_alert(self) -> None:
        cfg = self.config
        prev_i = (self._gw_cur - 1) % cfg.windows
        self.burn_short = self._burn(
            self._goodput_of([self._gw[self._gw_cur], self._gw[prev_i]]))
        self.burn_long = self._burn(self.goodput())
        state = self._alert_of(self.burn_short, self.burn_long)
        self._last_state_change = state != self.alert_state
        self.alert_state = state
        # per-class burns, same two-horizon formula over the class rings
        for cls, cw in self._cw.items():
            short = self._burn(
                self._goodput_of([cw[self._gw_cur], cw[prev_i]]))
            long = self._burn(self._goodput_of(cw))
            self.class_burns[cls] = [short, long]
            self.class_alerts[cls] = self._alert_of(short, long)

    def class_alert(self, cls: str) -> str:
        """Current burn-rate alert for one class (``ok`` when the class
        has never been observed)."""
        return self.class_alerts.get(cls, "ok")

    def _rotate(self) -> None:
        self.ttft.rotate()
        self.gap.rotate()
        self.e2e.rotate()
        self._gw_cur = (self._gw_cur + 1) % self.config.windows
        self._gw[self._gw_cur] = [0, 0]
        for cw in self._cw.values():
            cw[self._gw_cur] = [0, 0]
        self.rotations += 1
        self._steps_in_window = 0
        # quantile walks are O(buckets x windows); amortize them to
        # rotation boundaries so the per-step cost stays counters-only
        self._p99_cache = {
            "ttft_p99_ms": self.ttft.quantile(0.99),
            "gap_p99_ms": self.gap.quantile(0.99),
            "e2e_p99_ms": self.e2e.quantile(0.99),
        }

    def on_step(self, step: int = 0) -> None:
        """Once per serving step: window rotation, burn-rate/alert
        recompute, gauge + Perfetto counter export."""
        t0 = time.perf_counter_ns()
        self._steps_in_window += 1
        if self._steps_in_window >= self.config.window_steps:
            self._rotate()
        prev_state = self.alert_state
        self._recompute_alert()
        gp = self.goodput()
        level = _ALERT_LEVELS[self.alert_state]
        if self.registry is not None:
            if self._handles is None:
                # one registry (lock-taking) lookup per metric, ever
                g = self.registry.gauge
                self._handles = (g("slo/goodput"), g("slo/burn_short"),
                                 g("slo/burn_long"), g("slo/alert_level"),
                                 g("slo/ttft_p99_ms"), g("slo/gap_p99_ms"),
                                 g("slo/e2e_p99_ms"))
            h = self._handles
            h[0].set(gp)
            h[1].set(self.burn_short)
            h[2].set(self.burn_long)
            h[3].set(level)
            pc = self._p99_cache
            if pc:
                h[4].set(pc["ttft_p99_ms"])
                h[5].set(pc["gap_p99_ms"])
                h[6].set(pc["e2e_p99_ms"])
        if self.tracer is not None:
            self.tracer.counter("slo/goodput", goodput=gp,
                                burn_short=self.burn_short)
            self.tracer.counter("slo/alert", level=level)
        if self.alert_state != prev_state:
            if self.tracer is not None:
                self.tracer.instant("slo/alert_change",
                                    state=self.alert_state,
                                    burn_short=self.burn_short,
                                    burn_long=self.burn_long)
            if self.monitor is not None \
                    and getattr(self.monitor, "enabled", False):
                self.monitor.write_events([
                    ("telemetry/slo_alert", float(level), int(step))])
        self.overhead_ns += time.perf_counter_ns() - t0

    # -- lifecycle -----------------------------------------------------
    def reset(self) -> None:
        """Zero all windows/counters (keep config); benches call this
        after warmup so goodput covers only the measured interval."""
        for wq in (self.ttft, self.gap, self.e2e):
            for d in wq._ring:
                d.clear()
        self._gw = [[0, 0] for _ in range(self.config.windows)]
        self._gw_cur = 0
        self.admitted_total = 0
        self.finished_total = 0
        self.good_total = 0
        self.per_class = {}
        self._cw = {}
        self.class_alerts = {}
        self.class_burns = {}
        self.cancelled_total = 0
        self.alert_state = "ok"
        self.burn_short = 0.0
        self.burn_long = 0.0
        self.rotations = 0
        self._steps_in_window = 0
        self._p99_cache = {}
        self.overhead_ns = 0

    @property
    def overhead_s(self) -> float:
        return self.overhead_ns / 1e9

    def snapshot(self) -> Dict[str, Any]:
        ttft, gap, e2e = (self.ttft.merged(), self.gap.merged(),
                          self.e2e.merged())
        return {
            "goodput_slo": self.goodput(),
            "admitted": self.admitted_total,
            "finished": self.finished_total,
            "good": self.good_total,
            "burn_short": self.burn_short,
            "burn_long": self.burn_long,
            "alert_state": self.alert_state,
            "ttft_p50_ms": ttft.quantile(0.5),
            "ttft_p90_ms": ttft.quantile(0.9),
            "ttft_p99_ms": ttft.quantile(0.99),
            "gap_p50_ms": gap.quantile(0.5),
            "gap_p90_ms": gap.quantile(0.9),
            "gap_p99_ms": gap.quantile(0.99),
            "e2e_p99_ms": e2e.quantile(0.99),
            "cancelled": self.cancelled_total,
            "per_class": {
                k: {"admitted": v[0], "finished": v[1], "good": v[2],
                    "goodput_window": (self._goodput_of(self._cw[k])
                                       if k in self._cw else 1.0),
                    "burn_short": self.class_burns.get(k, [0.0, 0.0])[0],
                    "burn_long": self.class_burns.get(k, [0.0, 0.0])[1],
                    "alert": self.class_alerts.get(k, "ok")}
                for k, v in sorted(self.per_class.items())},
            "rotations": self.rotations,
            "windows": self.config.windows,
            "window_steps": self.config.window_steps,
            "overhead_s": self.overhead_s,
        }
