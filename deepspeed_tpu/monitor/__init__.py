"""Monitor namespace (≅ reference ``deepspeed.monitor``): the
``(tag, value, step)`` event sinks. Both training and the serving
subsystem emit through :class:`MonitorMaster`."""

from .monitor import (Event, Monitor, MonitorMaster,  # noqa: F401
                      TensorBoardMonitor, WandbMonitor, csvMonitor)

__all__ = ["Event", "Monitor", "MonitorMaster", "TensorBoardMonitor",
           "WandbMonitor", "csvMonitor"]
