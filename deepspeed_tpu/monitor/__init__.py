"""Monitor namespace (≅ reference ``deepspeed.monitor``): the
``(tag, value, step)`` event sinks. Both training and the serving
subsystem emit through :class:`MonitorMaster`."""

from .monitor import (Event, JSONLMonitor, Monitor,  # noqa: F401
                      MonitorMaster, TensorBoardMonitor, WandbMonitor,
                      csvMonitor)

__all__ = ["Event", "Monitor", "MonitorMaster", "TensorBoardMonitor",
           "WandbMonitor", "csvMonitor", "JSONLMonitor"]
