"""Experiment monitors.

Capability parity with reference ``deepspeed/monitor/monitor.py`` — ``Monitor``
ABC (:13) + ``MonitorMaster`` fan-out (:29) to TensorBoard
(monitor/tensorboard.py:13), W&B (monitor/wandb.py:12) and CSV
(monitor/csv_monitor.py:12), plus a dependency-free ``JSONLMonitor``
(one JSON object per event with a wall-clock timestamp — the machine-
readable sink telemetry flushes route through). Events are ``(tag,
value, step)`` tuples, written only from process 0 (rank gating as in
the reference).
"""

from __future__ import annotations

import abc
import csv
import json
import os
import time
from typing import List, Optional, Tuple

from ..utils.logging import logger

Event = Tuple[str, float, int]


def _is_rank_zero() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


class Monitor(abc.ABC):
    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.enabled = getattr(monitor_config, "enabled", False)

    @abc.abstractmethod
    def write_events(self, event_list: List[Event]) -> None:
        ...


class TensorBoardMonitor(Monitor):
    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        if self.enabled and _is_rank_zero():
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(tensorboard_config.output_path,
                                    tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as e:  # tensorboard optional
                logger.warning(f"TensorBoard monitor disabled: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event], flush: bool = True) -> None:
        if self.summary_writer is None:
            return
        for event in event_list:
            self.summary_writer.add_scalar(*event)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        if self.enabled and _is_rank_zero():
            try:
                import wandb

                wandb.init(project=wandb_config.project, group=wandb_config.group,
                           entity=wandb_config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"W&B monitor disabled: {e}")
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not (self.enabled and _is_rank_zero()):
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):
    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames: dict = {}
        if self.enabled and _is_rank_zero():
            self.log_dir = os.path.join(csv_config.output_path or "csv_monitor",
                                        csv_config.job_name)
            os.makedirs(self.log_dir, exist_ok=True)

    def write_events(self, event_list: List[Event]) -> None:
        if not (self.enabled and _is_rank_zero()):
            return
        for name, value, step in event_list:
            fname = os.path.join(self.log_dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, float(value)])


class JSONLMonitor(Monitor):
    """Append-only JSON-lines sink: one object per event, stamped with
    wall-clock time. No torch/wandb dependency — this is the sink
    machine consumers (and the telemetry registry flush) read back, so
    the format is one ``json.loads``-able line per event:

    ``{"tag": "serving/ttft_ms", "value": 6.7, "step": 42, "time": ...}``
    """

    def __init__(self, jsonl_config):
        super().__init__(jsonl_config)
        self.path: Optional[str] = None
        # failed write_events batches (disk full, permissions, path
        # yanked); scraped via the telemetry registry so sink failures
        # are visible instead of silently dropping data
        self.write_errors = 0
        if self.enabled and _is_rank_zero():
            log_dir = os.path.join(jsonl_config.output_path or "jsonl_monitor",
                                   jsonl_config.job_name)
            os.makedirs(log_dir, exist_ok=True)
            self.path = os.path.join(log_dir, "events.jsonl")

    def write_events(self, event_list: List[Event]) -> None:
        if self.path is None or not (self.enabled and _is_rank_zero()):
            return
        now = time.time()
        try:
            with open(self.path, "a") as fh:
                for name, value, step in event_list:
                    fh.write(json.dumps({"tag": name, "value": float(value),
                                         "step": int(step),
                                         "time": now}) + "\n")
        except OSError:
            # a telemetry sink must never take down the serving loop;
            # count and keep going (the gap is visible in write_errors)
            self.write_errors += 1


class MonitorMaster(Monitor):
    """Fan-out to all enabled monitors (reference monitor/monitor.py:29)."""

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        self.tb_monitor: Optional[TensorBoardMonitor] = None
        self.wandb_monitor: Optional[WandbMonitor] = None
        self.csv_monitor: Optional[csvMonitor] = None
        self.jsonl_monitor: Optional[JSONLMonitor] = None
        self.enabled = monitor_config.enabled
        if _is_rank_zero():
            if monitor_config.tensorboard.enabled:
                self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
            if monitor_config.wandb.enabled:
                self.wandb_monitor = WandbMonitor(monitor_config.wandb)
            if monitor_config.csv_monitor.enabled:
                self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
            if getattr(monitor_config, "jsonl", None) is not None and \
                    monitor_config.jsonl.enabled:
                self.jsonl_monitor = JSONLMonitor(monitor_config.jsonl)

    def write_events(self, event_list: List[Event]) -> None:
        if not _is_rank_zero():
            return
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor,
                  self.jsonl_monitor):
            if m is not None and m.enabled:
                m.write_events(event_list)
