"""N-dimensional cartesian process topology with named axes.

Capability parity with the reference's ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology`` at pipe/topology.py:12, axis comm-group enumeration at
:127, ``PipeDataParallelTopology`` :232, ``PipeModelDataParallelTopology``
:244). On TPU the *execution* grid is a ``jax.sharding.Mesh``; this class keeps
the pure-python rank/coordinate arithmetic that checkpoint naming, pipeline
scheduling, and group enumeration need, and can mint the matching Mesh.
"""

from __future__ import annotations

import itertools
from collections import namedtuple
from typing import Dict, List, Sequence


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates <-> linear global ranks.

    Axes are named and ordered major-to-minor: the *last* axis has
    adjacent-rank locality (on TPU, put the axis that should ride ICI last).
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError(f"axes {axes} and dims {dims} must have equal length")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict["ProcessTopology.ProcessCoord", int] = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self.dims])):
            self.mapping[self.ProcessCoord(*coord)] = rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {list(coord_kwargs)}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes: Sequence[str] = ("data",), inner_sep: str = "_",
                      outer_sep: str = "-") -> str:
        """String like ``pipe_00-model_00`` used in checkpoint file names."""
        omit = frozenset(omit_axes)
        axes = [a for a in self.axes if a not in omit]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology {self}")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Rank groups that vary only along ``axis`` — i.e. the communicator
        groups for that axis (reference pipe/topology.py:127)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i}, **fixed) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """All ranks whose coordinates match the given axis=value filters."""

        def _matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())

        return sorted(idx for coord, idx in self.mapping.items() if _matches(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return sorted(rank for coord, rank in self.mapping.items() if getattr(coord, axis) == idx)

    def world_size(self) -> int:
        import math

        return math.prod(self.dims)

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """pipe-major × data-minor topology (reference pipe/topology.py:232).

    Data-parallel ranks are adjacent (last axis) so DP collectives ride ICI.
    """

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe × data × model 3D topology (reference pipe/topology.py:244).

    Model (tensor) parallel is the innermost axis: TP collectives are the most
    latency-sensitive so they get adjacent devices.
    """

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])
