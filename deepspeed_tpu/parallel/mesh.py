"""Device-mesh construction and the global "process group" registry.

This is the TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py:46 initialize``, expert-group creation at
groups.py:108/202, world-group clone at :304, and ``PipelineParallelGrid`` at
``deepspeed/runtime/pipe/topology.py:251``). Instead of NCCL communicators,
every parallel axis is a named axis of one global ``jax.sharding.Mesh``;
"creating a group" is picking an axis (or tuple of axes) name.

Axis layout (major → minor): ``pipe, data, expert, seq, model``.

  - ``data``    — ZeRO/data parallelism. Non-expert parameters/grads/optimizer
                  state shard over ("data", "expert", "seq") combined (expert
                  and seq are size-1 unless enabled, so this degenerates to
                  pure DP).
  - ``expert``  — expert parallelism: a factor of the DP world carved out for
                  MoE all-to-all, mirroring _get_expert_parallel_ranks
                  (groups.py:156) where EP groups are sub-groups of DP.
  - ``seq``     — sequence/context parallelism (ring attention / Ulysses) —
                  beyond-parity axis, size 1 by default.
  - ``model``   — tensor (Megatron-style) model parallelism, innermost so its
                  collectives ride adjacent ICI links.
  - ``pipe``    — pipeline stages, outermost (cross-slice/DCN friendly).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import logger

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
DATA_OUTER_AXIS = "data_outer"  # MiCS replica groups (hierarchical ZeRO)
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"

MESH_AXES = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)
MICS_MESH_AXES = (PIPE_AXIS, DATA_OUTER_AXIS, DATA_AXIS, EXPERT_AXIS,
                  SEQ_AXIS, MODEL_AXIS)

# Axes over which ZeRO (sharded-DP) state is partitioned. `expert` and `seq`
# multiply into the ZeRO shard world when enabled: params/optimizer state may
# shard over `seq` too (grads are psummed over it by GSPMD since the sp group
# works on chunks of the SAME samples — ZeRO+Ulysses composition).
ZERO_AXES = (DATA_AXIS, EXPERT_AXIS, SEQ_AXIS)
# Axes over which the global batch (sample dim) is split. `seq` is NOT a
# batch axis: it shards the SEQUENCE dim of each sample (ring/Ulysses
# attention, ops/attention/sequence_parallel.py).
BATCH_AXES = (DATA_AXIS, EXPERT_AXIS)


def batch_axes() -> Tuple[str, ...]:
    """Batch (sample-dim) axes of the CURRENT mesh: includes the MiCS
    replica axis when present."""
    mesh = get_mesh() if has_mesh() else None
    if mesh is not None and DATA_OUTER_AXIS in mesh.axis_names:
        return (DATA_OUTER_AXIS,) + BATCH_AXES
    return BATCH_AXES


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Degrees of parallelism; -1 for data means "fill remaining devices"."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.model * self.pipe * self.expert * self.seq
        if n_devices % fixed != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by model×pipe×expert×seq = {fixed}")
        data = self.data
        if data == -1:
            data = n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}×{fixed} (dp×rest) != device count {n_devices}")
        return MeshConfig(data=data, model=self.model, pipe=self.pipe, expert=self.expert,
                          seq=self.seq)

    @property
    def dims(self) -> Tuple[int, ...]:
        return (self.pipe, self.data, self.expert, self.seq, self.model)


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None,
               *,
               data: int = -1,
               model: int = 1,
               pipe: int = 1,
               expert: int = 1,
               seq: int = 1,
               mics_shard_size: int = 0):
    """Build the global ``jax.sharding.Mesh``.

    Uses ``jax.experimental.mesh_utils.create_device_mesh`` when possible so
    the logical axes map onto the physical ICI torus well.
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(data=data, model=model, pipe=pipe, expert=expert, seq=seq)
    if devices is None:
        devices = jax.devices()
    config = config.resolve(len(devices))

    mics_shard_size = int(mics_shard_size or 0)
    dims = config.dims
    axes = MESH_AXES
    if mics_shard_size > config.data > 0:
        raise ValueError(
            f"mics_shard_size {mics_shard_size} exceeds the data-parallel "
            f"degree {config.data}")
    if mics_shard_size and 0 < mics_shard_size < config.data:
        # MiCS: factor data into (replica groups × shard group); ZeRO state
        # shards only over the inner group, replicating across groups —
        # hierarchical allgathers stay inside a group's ICI neighborhood
        if config.data % mics_shard_size != 0:
            raise ValueError(
                f"mics_shard_size {mics_shard_size} must divide data "
                f"parallel degree {config.data}")
        dims = (config.pipe, config.data // mics_shard_size,
                mics_shard_size, config.expert, config.seq, config.model)
        axes = MICS_MESH_AXES
    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(dims, devices=list(devices))
    except Exception:  # non-TPU platforms (CPU test meshes) lack torus metadata
        device_array = np.asarray(list(devices)).reshape(dims)
    return Mesh(device_array, axes)


class _GroupsState:
    """Global registry, the analog of the reference's module-level group dict
    in ``deepspeed/utils/groups.py``."""

    def __init__(self):
        self.mesh = None
        self.mesh_config: Optional[MeshConfig] = None
        self.topology: Optional["ProcessTopology"] = None


_state = _GroupsState()


def initialize_mesh(config: Optional[MeshConfig] = None, devices=None, **kwargs):
    """Create and install the global mesh (≅ ``groups.initialize``,
    reference utils/groups.py:46)."""
    mesh = build_mesh(config, devices, **kwargs)
    set_mesh(mesh)
    logger.info(f"initialized global mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    return mesh


def set_mesh(mesh) -> None:
    from .topology import ProcessTopology

    _state.mesh = mesh
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    _state.mesh_config = MeshConfig(
        data=dims.get(DATA_AXIS, 1) * dims.get(DATA_OUTER_AXIS, 1),
        model=dims.get(MODEL_AXIS, 1),
        pipe=dims.get(PIPE_AXIS, 1),
        expert=dims.get(EXPERT_AXIS, 1),
        seq=dims.get(SEQ_AXIS, 1),
    )
    _state.topology = ProcessTopology(list(mesh.axis_names), list(mesh.devices.shape))


def get_mesh():
    if _state.mesh is None:
        initialize_mesh()
    return _state.mesh


def has_mesh() -> bool:
    return _state.mesh is not None


def get_topology():
    get_mesh()
    return _state.topology


def reset_mesh() -> None:
    _state.mesh = None
    _state.mesh_config = None
    _state.topology = None


def _axis_size(axis: str) -> int:
    mesh = get_mesh()
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)


# --- world-size accessors, mirroring deepspeed/utils/groups.py getters ---
def get_data_parallel_world_size() -> int:
    """Number of model replicas in the batch sense — the multiplier in
    ``train_batch = micro_batch × gas × dp_world``. Excludes ``seq``: a
    sequence-parallel group cooperates on the *same* samples."""
    return math.prod(_axis_size(a) for a in batch_axes())


def get_model_parallel_world_size() -> int:
    return _axis_size(MODEL_AXIS)


def get_pipe_parallel_world_size() -> int:
    return _axis_size(PIPE_AXIS)


def get_expert_parallel_world_size() -> int:
    return _axis_size(EXPERT_AXIS)


def get_sequence_parallel_world_size() -> int:
    return _axis_size(SEQ_AXIS)


def get_world_size() -> int:
    mesh = get_mesh()
    return mesh.devices.size
