"""t5x-style logical axis rules: named model dims -> mesh axes.

Modules annotate parameters and cache containers with LOGICAL axis
names ("heads", "ffn", "slots", ...) instead of hard-coding mesh axes;
an ordered rules table (first match wins, ≅ t5x
``LogicalAxisRules`` / flax ``logical_to_mesh``) maps each logical
name to a physical mesh axis from :data:`~.mesh.MESH_AXES`. One table
swap re-partitions the whole serving stack — the modules never change.

Resolution is SHAPE-AWARE, which is what keeps re-partitioning
recompile-free and bitwise-safe in practice:

* a mesh axis of size 1 is dropped from the resolved spec (partitioning
  over one device is replication; keeping the name would give the
  committed arrays a *different but equivalent* sharding from what
  GSPMD stamps on jit outputs, forking every donated-pool executable —
  the PR-5 double-executable class). A TP=1 mesh therefore resolves
  every rule to the fully-replicated spec the engine uses today, which
  is how TP=1 stays bitwise-identical by construction;
* a dimension the mapped axis size does not divide falls back to
  replicated for THAT dimension only (t5x's divisibility fallback), so
  a 4-slot pool on a data=8 CPU test mesh keeps working instead of
  failing in ``device_put``;
* a mesh axis already consumed by an earlier dimension is not repeated
  (PartitionSpec forbids duplicate axes) — later dimensions replicate.

The table's mesh-axis names are pinned against the statically-declared
universe in ``parallel/mesh.py`` both at runtime
(:func:`validate_axis_rules` at import) and statically (graftcheck's
``mesh-axis-unknown`` rule reads the same constants).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec

from . import mesh as mesh_mod
from .mesh import DATA_AXIS, DATA_OUTER_AXIS, MESH_AXES, MODEL_AXIS

#: logical name -> mesh axis (None = always replicated). Ordered,
#: first match wins. ``heads``/``kv_heads``/``ffn``/``vocab`` carry the
#: Megatron TP sharding (column/row-parallel projections, vocab-parallel
#: embedding — the reference's ``module_inject``/AutoTP placement);
#: ``slots`` is the serving batch dimension (slot-pooled KV rows) and
#: shards over the data axis; ``pages`` stays replicated — the paged
#: pool's free list is host-global, so pages must be reachable from
#: every data shard.
DEFAULT_AXIS_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("layers", None),
    ("embed", None),
    ("vocab", MODEL_AXIS),
    ("heads", MODEL_AXIS),
    ("kv_heads", MODEL_AXIS),
    ("head_dim", None),
    ("ffn", MODEL_AXIS),
    ("slots", DATA_AXIS),
    ("pages", None),
    ("positions", None),
)

#: logical layouts of the serving cache containers (KVCacheSpec
#: layouts; models/transformer_lm.py is the shape source of truth)
STACKED_CACHE_AXES: Dict[str, Tuple[Optional[str], ...]] = {
    "k": ("layers", "slots", "kv_heads", "head_dim", "positions"),
    "v": ("layers", "slots", "kv_heads", "head_dim", "positions"),
    "k_scale": ("layers", "slots", "kv_heads", "positions"),
    "v_scale": ("layers", "slots", "kv_heads", "positions"),
    "index": ("slots",),
}
PAGED_CACHE_AXES: Dict[str, Tuple[Optional[str], ...]] = {
    "k": ("layers", "pages", "kv_heads", "head_dim", "positions"),
    "v": ("layers", "pages", "kv_heads", "head_dim", "positions"),
    "k_scale": ("layers", "pages", "kv_heads", "positions"),
    "v_scale": ("layers", "pages", "kv_heads", "positions"),
    "index": ("slots",),
    "table": ("slots", None),
}


def validate_axis_rules(
        rules: Sequence[Tuple[str, Optional[str]]]) -> None:
    """Pin every mesh-axis name in ``rules`` against the mesh universe
    declared in :mod:`.mesh` (``MESH_AXES`` + the MiCS outer axis).
    A typo'd axis name would otherwise surface as a silent
    fully-replicated placement — NamedSharding accepts any string the
    mesh happens to contain, and a name the mesh does NOT contain only
    fails at ``device_put`` time deep inside an engine."""
    universe = set(MESH_AXES) | {DATA_OUTER_AXIS}
    for logical, axis in rules:
        if not isinstance(logical, str) or not logical:
            raise ValueError(f"logical axis name must be a non-empty "
                             f"string, got {logical!r}")
        if axis is not None and axis not in universe:
            raise ValueError(
                f"axis rule ({logical!r} -> {axis!r}) names a mesh axis "
                f"outside the declared universe {sorted(universe)}")


class LogicalAxisRules:
    """Ordered logical->mesh axis table with shape-aware resolution."""

    def __init__(self, rules: Sequence[Tuple[str, Optional[str]]]
                 = DEFAULT_AXIS_RULES):
        validate_axis_rules(rules)
        self.rules: Tuple[Tuple[str, Optional[str]], ...] = tuple(
            (str(l), a) for l, a in rules)

    def mesh_axis(self, logical: Optional[str]) -> Optional[str]:
        """First matching mesh axis for ``logical`` (None if the name is
        None, unmatched, or mapped to replicated)."""
        if logical is None:
            return None
        for name, axis in self.rules:
            if name == logical:
                return axis
        return None

    def spec_entries(self, logical_axes: Sequence[Optional[str]]
                     ) -> Tuple[Optional[str], ...]:
        """Mesh-axis tuple for a logical layout, UNRESOLVED (no shape or
        mesh applied) — the ``(axis_or_None, ...)`` form the module
        sharding-rule tables and ``ShardingRules.spec_for`` trade in."""
        return tuple(self.mesh_axis(l) for l in logical_axes)

    def spec_for(self, logical_axes: Sequence[Optional[str]],
                 shape: Optional[Sequence[int]] = None,
                 mesh: Any = None) -> PartitionSpec:
        """Resolve a logical layout to a PartitionSpec against ``mesh``
        (default: the global mesh), applying the size-1 normalization,
        divisibility fallback, and duplicate-axis suppression documented
        in the module docstring."""
        if mesh is None and mesh_mod.has_mesh():
            mesh = mesh_mod.get_mesh()
        entries = self.spec_entries(logical_axes)
        if shape is not None and len(shape) != len(entries):
            raise ValueError(
                f"logical layout {tuple(logical_axes)} has "
                f"{len(entries)} axes but shape {tuple(shape)} has "
                f"{len(shape)} dims")
        return physical_spec(entries, shape=shape, mesh=mesh)

    def sharding_for(self, logical_axes: Sequence[Optional[str]],
                     shape: Optional[Sequence[int]] = None,
                     mesh: Any = None) -> NamedSharding:
        if mesh is None:
            mesh = mesh_mod.get_mesh()
        return NamedSharding(
            mesh, self.spec_for(logical_axes, shape=shape, mesh=mesh))


def physical_spec(entries: Sequence[Optional[str]],
                  shape: Optional[Sequence[int]] = None,
                  mesh: Any = None) -> PartitionSpec:
    """Guard a raw ``(axis_or_None, ...)`` placement into a spec that is
    always safe to commit: drop size-1 axes, drop axes that do not
    divide their dimension (when ``shape`` is known), never repeat a
    mesh axis. Shared by the rules table and the inference engine's
    parameter placement (AutoTP specs get the same divisibility guard)."""
    sizes = dict(getattr(mesh, "shape", None) or {}) if mesh is not None \
        else {}
    out = []
    used = set()
    for i, axis in enumerate(entries):
        if axis is None or axis in used:
            out.append(None)
            continue
        size = sizes.get(axis) if sizes else None
        if mesh is not None and size is None:
            out.append(None)          # axis absent from this mesh
            continue
        if size is not None and size <= 1:
            out.append(None)          # partitioning over 1 device =
            continue                  # replication; keep specs canonical
        if shape is not None and size is not None \
                and int(shape[i]) % int(size) != 0:
            out.append(None)          # t5x divisibility fallback
            continue
        out.append(axis)
        used.add(axis)
    while out and out[-1] is None:    # canonical: no trailing Nones, so
        out.pop()                     # P() == fully replicated compares
    return PartitionSpec(*out)        # equal across call sites


_DEFAULT_RULES: Optional[LogicalAxisRules] = None


def default_axis_rules() -> LogicalAxisRules:
    """The process-wide default table (validated once, cached)."""
    global _DEFAULT_RULES
    if _DEFAULT_RULES is None:
        _DEFAULT_RULES = LogicalAxisRules(DEFAULT_AXIS_RULES)
    return _DEFAULT_RULES


def cache_leaf_sharding(kind: str, mesh: Any = None,
                        rules: Optional[LogicalAxisRules] = None):
    """Per-leaf sharding resolver for a serving cache container —
    the callable form :class:`~..serving.slot_pool.SlotPool` /
    :class:`~..serving.paged_pool.PagedKVPool` accept through their
    ``sharding`` seam. ``kind`` is ``"stacked"`` or ``"paged"``; the
    returned ``fn(key, leaf) -> NamedSharding`` resolves that
    container's logical layout against ``leaf``'s actual shape, so
    indivisible dims (a 4-slot pool on a data=8 mesh) replicate instead
    of failing, and a TP=1 mesh resolves every leaf to the replicated
    placement the pools committed before this seam existed."""
    layouts = {"stacked": STACKED_CACHE_AXES,
               "paged": PAGED_CACHE_AXES}[kind]
    rules = rules if rules is not None else default_axis_rules()

    def leaf_sharding(key: str, leaf: Any) -> NamedSharding:
        m = mesh if mesh is not None else mesh_mod.get_mesh()
        axes = layouts.get(key)
        shape = getattr(leaf, "shape", None)
        if axes is None or shape is None or len(axes) != len(shape):
            return NamedSharding(m, PartitionSpec())
        return rules.sharding_for(axes, shape=shape, mesh=m)

    return leaf_sharding
