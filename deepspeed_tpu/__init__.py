"""deepspeed_tpu — a TPU-native training & inference framework with the
capabilities of DeepSpeed (reference v0.9.5), built on JAX/XLA/pjit/Pallas.

Public API parity with ``deepspeed/__init__.py``: :func:`initialize` (:58),
:func:`init_inference` (:260), :func:`add_config_arguments` (:237), plus the
``comm``/``zero``/``monitor``/``ops`` subpackages.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

__version__ = "0.1.0"

from . import comm  # noqa: F401
from . import parallel  # noqa: F401
from .runtime import zero  # noqa: F401  (deepspeed.zero namespace parity)
from .runtime.activation_checkpointing import checkpointing  # noqa: F401
from .accelerator import get_accelerator  # noqa: F401
from .runtime.config import DeepSpeedConfig  # noqa: F401
from .runtime.engine import DeepSpeedEngine  # noqa: F401
from .utils.logging import log_dist, logger  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401  (≅ reference
# deepspeed.init_distributed, deepspeed/__init__.py:303 re-export)


def initialize(args=None,
               model: Any = None,
               optimizer=None,
               model_parameters: Any = None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               config: Union[str, Dict, None] = None,
               config_params: Union[str, Dict, None] = None,
               loss_fn=None,
               sharding_rules=None,
               mesh=None):
    """Build the engine (≅ reference ``deepspeed.initialize``,
    deepspeed/__init__.py:58).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    TPU-native notes: ``model`` is a flax Module (``__call__(batch) -> loss``)
    or a pure ``loss_fn(params, batch, rng)``; ``optimizer`` comes from the
    JSON config (``optimizer.type``); ``mpu`` is superseded by the mesh —
    pass ``mesh`` or config["mesh"] degrees instead.
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("DeepSpeed requires --deepspeed_config or config=")

    # PipelineModule → PipelineEngine dispatch (reference __init__.py:151-189)
    try:
        from .runtime.pipe.module import PipelineModule
    except ImportError:
        PipelineModule = None

    cfg_probe = config
    if isinstance(config, str):
        import json as _json

        with open(config) as _fh:
            cfg_probe = _json.load(_fh)
    hybrid = isinstance(cfg_probe, dict) and \
        cfg_probe.get("hybrid_engine", {}).get("enabled", False)
    if not hybrid and hasattr(cfg_probe, "hybrid_engine"):
        hybrid = bool(cfg_probe.hybrid_engine.enabled)
    if PipelineModule is not None and isinstance(model, PipelineModule):
        from .runtime.pipe.engine import PipelineEngine

        engine = PipelineEngine(model=model, config=config,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler, collate_fn=collate_fn,
                                mesh=mesh, sharding_rules=sharding_rules)
    elif hybrid:
        from .runtime.hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(model=model, loss_fn=loss_fn,
                                       model_parameters=model_parameters,
                                       config=config,
                                       sharding_rules=sharding_rules,
                                       training_data=training_data,
                                       lr_scheduler=lr_scheduler,
                                       collate_fn=collate_fn, mesh=mesh)
    else:
        engine = DeepSpeedEngine(model=model, loss_fn=loss_fn,
                                 model_parameters=model_parameters,
                                 config=config, sharding_rules=sharding_rules,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler, collate_fn=collate_fn,
                                 mesh=mesh)
    return engine, engine.optimizer_def, engine.training_dataloader, engine.lr_scheduler


def init_inference(model: Any = None, config: Union[str, Dict, None] = None, **kwargs):
    """Build the inference engine (≅ reference ``deepspeed.init_inference``,
    deepspeed/__init__.py:260)."""
    try:
        from .inference.engine import InferenceEngine
    except ImportError as e:
        raise NotImplementedError(
            "inference engine not built yet in this round") from e

    return InferenceEngine(model=model, config=config, **kwargs)


def init_serving(model: Any = None, config: Union[str, Dict, None] = None,
                 num_slots: int = 4, max_queue_depth: int = 64, **kwargs):
    """Build a continuous-batching server: :func:`init_inference` for the
    engine, then wrap it in :class:`serving.ServingEngine` (slot-pooled KV
    cache, FIFO admission, per-request SLO metrics, optional speculative
    decoding).

    Knobs split into two scopes. **Server-global** (fixed at construction,
    shared by every request — they shape the compiled programs): the
    serving-only keys ``policy``, ``do_sample``, ``temperature``,
    ``top_k``, ``top_p``, ``seed``, ``monitor``, ``spec_decode``,
    ``prefill_chunk`` and ``prefill_token_budget`` (stall-free chunked
    admission; 0 disables), the telemetry keys ``tracer`` (a
    :class:`telemetry.Tracer`, or ``True`` for a default-capacity one),
    ``registry``, ``strict_recompile`` (raise at the step boundary on
    any post-warmup recompile) and ``timeline_capacity``, which pass
    through to ServingEngine, plus
    ``num_slots`` / ``max_queue_depth``. **Per-request** (ride on each ``submit()``):
    ``max_new_tokens`` and ``eos_token_id`` — nothing else varies per
    request, so slot churn never changes a compiled shape. Everything
    else configures the inference engine.

    ``spec_decode`` enables draft–verify speculative decoding: ``True``
    for defaults (n-gram drafter, k=4), a dict such as
    ``{"drafter": "ngram", "k": 8, "max_ngram": 3}`` or
    ``{"drafter": "model", "draft_engine": small_engine}``, or a
    :class:`serving.SpecDecodeConfig`. Greedy output stays bitwise
    identical to ``spec_decode=None``; admission control tightens to
    ``prompt + max_new_tokens <= capacity - k`` (the verify headroom).

    The fault-tolerance keys (all optional, all server-global):
    ``deadline_default_ms`` (TTL applied to every submit that doesn't
    carry its own ``deadline_ms``), ``step_wall_budget_ms`` (per-step
    wall-time watchdog), ``guard_numerics`` (NaN/inf logits guard that
    fails only the poisoned slot), ``degradation`` (``True``, a dict of
    :class:`serving.resilience.DegradationConfig` overrides, or an
    instance — the HEALTHY/PRESSURED/OVERLOADED ladder),
    ``preempt_queue_threshold`` / ``preempt_min_run_steps`` (automatic
    pressure preemption), and ``fault_injector`` (a
    :class:`serving.resilience.FaultInjector` for chaos testing).
    Per-request ``deadline_ms`` rides on ``submit()``.

    ``paged_kv`` replaces the per-slot contiguous KV rows with a
    :class:`serving.PagedKVPool` — fixed-size refcounted pages behind a
    static per-slot page table, with radix-trie prefix caching and
    copy-on-write sharing (vLLM PagedAttention + SGLang RadixAttention;
    greedy output stays bitwise identical). ``True`` for defaults (page
    size = the prefill chunk, ``num_pages`` = worst-case), or a dict
    ``{"num_pages": int, "page_size": int, "prefix_cache": bool,
    "kernel": "auto"|"on"|"off"}`` — ``num_pages`` below
    ``num_slots * max_seq_len / page_size`` oversubscribes HBM;
    pressure is drained by trie eviction, then automatic preemption.
    ``kernel`` selects the fused Pallas paged-attention decode/verify
    path (``"auto"`` arms it on real TPU hardware only; the dense
    gather path stays the bitwise-parity oracle). ``overlap`` pipelines
    ``step()`` — decode dispatches first, host bookkeeping overlaps the
    in-flight device work, and token fetches collapse onto one
    end-of-step sync — with outcomes bitwise identical to the serial
    step.

    The efficiency/goodput observability keys (all server-global):
    ``cost_model`` (``True``, a :class:`telemetry.ProgramCostModel`
    kwargs dict, or an instance — harvests XLA ``cost_analysis()`` per
    program and derives live MFU / bandwidth-utilization / KV-HBM-drift
    gauges; off by default because the lazy AOT harvest compiles each
    program once more), ``slo`` (``True``, a dict, or a
    :class:`telemetry.SLOConfig` — windowed quantile digests, goodput
    and burn-rate alerting), ``flight_recorder`` (on by default;
    ``False``, an int capacity, a kwargs dict, or a
    :class:`telemetry.FlightRecorder`), and ``dump_dir`` (where fatal
    raises drop their post-mortem JSON; ``srv.debug_dump()`` serves the
    same snapshot live).

    The multi-tenant front-end keys (server-global): ``priority``
    (``True`` for the default interactive/standard/batch classes, a
    :class:`serving.PriorityConfig` kwargs dict — ``classes``,
    ``shares``, ``default_class``, ``tenants`` — or an instance; swaps
    the FIFO scheduler for :class:`serving.PriorityScheduler` with
    fair-share token budgets, per-tenant rate limits/quotas, and
    burn-rate-driven shedding/preemption when ``slo`` is also on) and
    ``clock`` (a monotonic ``() -> float`` callable shared by EVERY
    time-dependent decision — deadlines, queue expiry, SLO latencies,
    rate buckets; defaults to ``time.perf_counter``; never wall
    clock). Per-request ``priority`` / ``tenant`` ride on ``submit()``.
    The HTTP/SSE server wraps the returned engine:
    ``serving.ServingFrontend(srv, port=...)``."""
    from .serving.engine import ServingEngine

    serve_keys = ("policy", "do_sample", "temperature", "top_k", "top_p",
                  "seed", "monitor", "spec_decode", "prefill_chunk",
                  "prefill_token_budget", "tracer", "registry",
                  "strict_recompile", "timeline_capacity",
                  "deadline_default_ms", "step_wall_budget_ms",
                  "guard_numerics", "degradation",
                  "preempt_queue_threshold", "preempt_min_run_steps",
                  "fault_injector", "paged_kv", "overlap", "cost_model",
                  "slo", "flight_recorder", "dump_dir", "priority", "clock")
    serve_kwargs = {k: kwargs.pop(k) for k in serve_keys if k in kwargs}
    engine = init_inference(model=model, config=config, **kwargs)
    return ServingEngine(engine, num_slots=num_slots,
                         max_queue_depth=max_queue_depth, **serve_kwargs)


def add_config_arguments(parser):
    """Inject --deepspeed / --deepspeed_config CLI args (≅ reference
    deepspeed/__init__.py:237)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity only)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
