"""Node-local launcher.

Capability parity with reference ``deepspeed/launcher/launch.py:132 main()``
— decodes the base64 world info, computes this node's global ranks, forks
one training process per local rank with ``RANK/WORLD_SIZE/MASTER_*`` env
set, installs a sigkill handler that tears the whole local group down when
any rank dies (:313), and routes to the elastic agent when
``--enable_elastic_training``.

TPU process model: normally ONE process per host drives all local chips
(``jax.distributed.initialize`` + every local device visible), so the world
info maps hosts → process slots rather than GPU ids. Per-chip processes are
still expressible (slots > 1) for CPU-mesh testing.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time
from collections import defaultdict
from typing import Dict, List

from ..utils.logging import logger

PID_FILE_BASEPATH = "/tmp"


def parse_args():
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU node-local launcher")
    parser.add_argument("--node_rank", type=int, default=0,
                        help="rank of this node in the multi-node job")
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("--world_info", default="None", type=str,
                        help="base64-encoded json of {host: [slots]}")
    parser.add_argument("--enable_elastic_training", action="store_true")
    parser.add_argument("--max_elastic_restarts", type=int, default=3)
    parser.add_argument("--save_pid", type=int, default=0,
                        help="write a launcher pid file for ds_ssh cleanup")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args()


def decode_world_info(world_info_b64: str) -> Dict[str, List[int]]:
    if world_info_b64 in (None, "None", ""):
        return {}
    decoded = base64.urlsafe_b64decode(world_info_b64)
    return json.loads(decoded)


def main(args=None):
    args = args or parse_args()
    world_info = decode_world_info(args.world_info)
    if not world_info:
        world_info = {"localhost": [0]}
    logger.info(f"launch: world_info={world_info} node_rank={args.node_rank}")

    node_list = list(world_info.keys())
    nnodes = len(node_list)
    if args.node_rank >= nnodes:
        raise ValueError(
            f"node_rank {args.node_rank} >= number of nodes {nnodes}")
    local_slots = world_info[node_list[args.node_rank]]
    num_local_procs = len(local_slots)

    # global rank offset = slots on the preceding nodes
    global_rank_offset = 0
    for i in range(args.node_rank):
        global_rank_offset += len(world_info[node_list[i]])
    world_size = sum(len(s) for s in world_info.values())

    if args.enable_elastic_training:
        from ..elasticity.elastic_agent import DSElasticAgent, WorkerSpec

        spec = WorkerSpec(
            entrypoint=[sys.executable, "-u", args.user_script] +
            args.user_args,
            local_world_size=num_local_procs,
            master_addr=args.master_addr, master_port=args.master_port,
            max_restarts=args.max_elastic_restarts,
            node_rank=args.node_rank, nnodes=nnodes,
            global_rank_offset=global_rank_offset, world_size=world_size)
        agent = DSElasticAgent(spec)
        sys.exit(agent.run())

    processes: List[subprocess.Popen] = []
    for local_rank, slot in enumerate(local_slots):
        env = dict(os.environ)
        env.update({
            "LOCAL_RANK": str(local_rank),
            "RANK": str(global_rank_offset + local_rank),
            "LOCAL_SIZE": str(num_local_procs),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": args.master_addr,
            "MASTER_PORT": str(args.master_port),
            # jax.distributed.initialize contract
            "JAX_COORDINATOR_ADDRESS":
                f"{args.master_addr}:{args.master_port}",
            "JAX_PROCESS_ID": str(global_rank_offset + local_rank),
            "JAX_NUM_PROCESSES": str(world_size),
        })
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        processes.append(subprocess.Popen(cmd, env=env))

    if args.save_pid:
        pid_path = os.path.join(PID_FILE_BASEPATH,
                                f"ds_tpu_{args.save_pid}.pids")
        with open(pid_path, "w") as f:
            f.write(",".join(str(p.pid) for p in processes))

    def sigkill_handler(signum, frame):
        # any-rank-dies ⇒ whole local group dies (reference launch.py:313)
        for p in processes:
            if p.poll() is None:
                p.terminate()
        logger.error(f"launch: received signal {signum}, killed local group")
        sys.exit(1)

    signal.signal(signal.SIGTERM, sigkill_handler)
    signal.signal(signal.SIGINT, sigkill_handler)

    alive = set(range(len(processes)))
    exit_code = 0
    while alive:
        for i in sorted(alive):
            code = processes[i].poll()
            if code is None:
                continue
            alive.discard(i)
            if code != 0:
                logger.error(
                    f"launch: rank {global_rank_offset + i} exited with "
                    f"code {code}; terminating local group")
                for p in processes:
                    if p.poll() is None:
                        p.terminate()
                sys.exit(code)
        time.sleep(0.5)
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
