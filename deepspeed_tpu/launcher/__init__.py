from .runner import (
    encode_world_info,
    fetch_hostfile,
    parse_inclusion_exclusion,
)

__all__ = ["fetch_hostfile", "parse_inclusion_exclusion", "encode_world_info"]
