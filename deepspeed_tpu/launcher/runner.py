"""Job launcher CLI.

Capability parity with reference ``deepspeed/launcher/runner.py:382 main()``
— hostfile parsing (:194,207), ``--include/--exclude`` resource filtering
(:249), base64 world-info encoding (:347), multi-node runner selection, and
single-node fall-through to the node-local launcher. Invoke as
``python -m deepspeed_tpu.launcher.runner`` (≅ the ``deepspeed`` CLI).

Hostfile format (reference parity)::

    worker-1 slots=4
    worker-2 slots=4

On TPU, ``slots`` is the number of launcher *processes* per host (1 for the
standard one-process-per-host JAX model).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger
from .multinode_runner import (
    IMPIRunner,
    MPICHRunner,
    MVAPICHRunner,
    OpenMPIRunner,
    PDSHRunner,
    SlurmRunner,
)

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "XLA_FLAGS", "JAX_PLATFORMS",
               "LD_LIBRARY_PATH", "TPU_LIBRARY_PATH"]
PDSH_LAUNCHER = "pdsh"
OPENMPI_LAUNCHER = "openmpi"
MPICH_LAUNCHER = "mpich"
IMPI_LAUNCHER = "impi"
SLURM_LAUNCHER = "slurm"
MVAPICH_LAUNCHER = "mvapich"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher: starts a multi-host training "
        "job from a hostfile")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile path: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="nodes/slots to include, e.g. "
                        "'worker-1@worker-2:0,2' limits hosts and slots")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="nodes/slots to exclude, e.g. 'worker-1:0'")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit the number of nodes")
    parser.add_argument("--min_elastic_nodes", type=int, default=-1)
    parser.add_argument("--max_elastic_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_accelerators", type=int,
                        default=-1, dest="num_gpus",
                        help="processes per node (TPU: usually 1/host)")
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("--master_addr", default="", type=str)
    parser.add_argument("--launcher", default=PDSH_LAUNCHER, type=str,
                        help="multi-node launcher backend: pdsh, openmpi, "
                        "mpich, impi, slurm, mvapich")
    parser.add_argument("--launcher_args", default="", type=str)
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--autotuning", default="", choices=["", "tune", "run"],
                        type=str, help="run the autotuner before launching")
    parser.add_argument("--elastic_training", action="store_true")
    parser.add_argument("--max_elastic_restarts", type=int, default=3)
    parser.add_argument("--bind_cores_to_rank", action="store_true")
    parser.add_argument("--ssh_port", type=int, default=None)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse '<host> slots=<n>' lines — reference runner.py:194."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"hostfile: unable to parse line: {line!r}")
                raise ValueError(f"hostfile {hostfile_path} has a bad line: "
                                 f"{line!r} (expected '<host> slots=<n>')")
            if hostname in resource_pool:
                raise ValueError(f"hostfile contains duplicate host "
                                 f"{hostname}")
            resource_pool[hostname] = slot_count
    if not resource_pool:
        return None
    return resource_pool


def _parse_hostfile_filter(s: str) -> Dict[str, Optional[List[int]]]:
    """'worker-0@worker-1:0,2' → {worker-0: None, worker-1: [0, 2]}."""
    mapping: Dict[str, Optional[List[int]]] = {}
    for node_config in s.split("@"):
        if not node_config:
            continue
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            mapping[hostname] = [int(x) for x in slots.split(",")]
        else:
            mapping[node_config] = None
    return mapping


def parse_inclusion_exclusion(resource_pool: Dict[str, int], inclusion: str,
                              exclusion: str) -> Dict[str, List[int]]:
    """Apply --include/--exclude — reference runner.py:249. Returns
    {host: [slot ids]}."""
    active: "OrderedDict[str, List[int]]" = OrderedDict()
    for host, slots in resource_pool.items():
        active[host] = list(range(slots))

    if inclusion:
        included = _parse_hostfile_filter(inclusion)
        for host in included:
            if host not in active:
                raise ValueError(f"include host {host} not in hostfile")
        new_active: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in included.items():
            new_active[host] = slots if slots is not None else active[host]
        active = new_active

    if exclusion:
        excluded = _parse_hostfile_filter(exclusion)
        for host, slots in excluded.items():
            if host not in active:
                raise ValueError(f"exclude host {host} not in hostfile")
            if slots is None:
                del active[host]
            else:
                active[host] = [s for s in active[host] if s not in slots]
                if not active[host]:
                    del active[host]
    return dict(active)


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def main(args=None):
    args = parse_args(args)

    resource_pool = fetch_hostfile(args.hostfile)
    multi_node = resource_pool is not None and len(resource_pool) > 1
    if not resource_pool:
        slots = args.num_gpus if args.num_gpus > 0 else 1
        resource_pool = {"localhost": slots}

    if args.num_nodes > 0:
        resource_pool = OrderedDict(
            list(resource_pool.items())[:args.num_nodes])
    if args.num_gpus > 0:
        resource_pool = OrderedDict(
            (h, args.num_gpus) for h in resource_pool)

    active_resources = parse_inclusion_exclusion(resource_pool, args.include,
                                                 args.exclude)
    if not active_resources:
        raise RuntimeError("no active resources after include/exclude")

    if not args.master_addr:
        first = list(active_resources.keys())[0]
        args.master_addr = "127.0.0.1" if first == "localhost" else first

    if args.autotuning:
        from ..autotuning.autotuner import run_autotuning

        run_autotuning(args, active_resources)
        return

    world_info_b64 = encode_world_info(active_resources)
    env = dict(os.environ)

    if not multi_node and not args.force_multi:
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={world_info_b64}", "--node_rank=0",
               f"--master_addr={args.master_addr}",
               f"--master_port={args.master_port}"]
        if args.elastic_training:
            cmd += ["--enable_elastic_training",
                    f"--max_elastic_restarts={args.max_elastic_restarts}"]
        cmd += [args.user_script] + args.user_args
        logger.info(f"cmd = {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=env)
        result.wait()
        sys.exit(result.returncode)

    # multi-node
    if args.launcher == PDSH_LAUNCHER:
        runner = PDSHRunner(args, world_info_b64)
    elif args.launcher == OPENMPI_LAUNCHER:
        runner = OpenMPIRunner(args, world_info_b64, active_resources)
    elif args.launcher == MPICH_LAUNCHER:
        runner = MPICHRunner(args, world_info_b64, active_resources)
    elif args.launcher == IMPI_LAUNCHER:
        runner = IMPIRunner(args, world_info_b64, active_resources)
    elif args.launcher == SLURM_LAUNCHER:
        runner = SlurmRunner(args, world_info_b64, active_resources)
    elif args.launcher == MVAPICH_LAUNCHER:
        runner = MVAPICHRunner(args, world_info_b64, active_resources)
    else:
        raise NotImplementedError(f"unknown launcher {args.launcher}")

    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher} not installed")

    for var in EXPORT_ENVS:
        if var in env:
            runner.add_export(var, env[var])
    cmd = runner.get_cmd(env, active_resources)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
