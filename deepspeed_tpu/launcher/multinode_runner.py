"""Multi-node runner command builders.

Capability parity with reference ``deepspeed/launcher/multinode_runner.py`` —
PDSH (:51), OpenMPI (:107), MPICH (:160), IMPI (:231), SLURM (:313),
MVAPICH (:361). Each runner turns (resource pool, user cmd) into the
command line that starts one node-local launcher per host. The TPU twist:
one *process per host* drives all local chips (the JAX process model), so
``--num_gpus`` here means processes-per-node and is 1 for TPU pods unless
megacore-style per-chip processes are requested.
"""

from __future__ import annotations

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote
from typing import Dict, List


def _uniform_slot_counts(resource_pool: Dict[str, List[int]],
                         backend: str) -> "tuple[int, int]":
    """(total processes, processes per node) from a host→slot-id-list pool.

    MPI-family runners address ranks as ``-n total -ppn per_node`` and so
    require every node to expose the same slot count; raise otherwise.
    """
    per_node = [len(slots) for slots in resource_pool.values()]
    if not per_node:
        raise ValueError(f"{backend} launch requires a non-empty resource pool")
    if any(n != per_node[0] for n in per_node):
        raise ValueError(
            f"{backend} requires the same number of devices per node, "
            f"got {dict(zip(resource_pool, per_node))}")
    return sum(per_node), per_node[0]


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def backend_exists(self) -> bool:
        ...

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        ...

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    @property
    def name(self) -> str:
        return self.__class__.__name__


class PDSHRunner(MultiNodeRunner):
    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def _launcher_argv(self) -> List[str]:
        """Argv of the node-local launcher module; %n is pdsh's node-rank token."""
        argv = [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            "--node_rank=%n",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
        ]
        if getattr(self.args, "elastic_training", False):
            argv += ["--enable_elastic_training",
                     f"--max_elastic_restarts={self.args.max_elastic_restarts}"]
        return argv + [self.user_script] + [quote(a) for a in self.user_arguments]

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        # The remote side gets ONE shell string: env exports, then cd into the
        # same working directory the user launched from, then the node-local
        # launcher. pdsh fans it out to every active host (-S propagates the
        # worst exit code back; -f caps ssh fanout).
        remote = [f"export {k}={quote(v)};" for k, v in self.exports.items()]
        remote.append(f"cd {os.path.abspath('.')};")
        remote.extend(self._launcher_argv())
        hosts = ",".join(active_resources.keys())
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, " ".join(remote)]


class OpenMPIRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("UCX_TLS", "tcp")

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total_process_count = sum(len(v) for v in self.resource_pool.values())
        mpirun_cmd = [
            "mpirun", "-n", f"{total_process_count}", "-hostfile",
            self.args.hostfile, "--mca", "btl", "^openib", "--mca",
            "btl_tcp_if_include", "eth0",
        ]
        export_cmd = []
        # argv values go through Popen without a shell — no quoting, or the
        # quotes end up literally inside the env value
        for key, val in self.exports.items():
            export_cmd += ["-x", f"{key}={val}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + \
            list(self.user_arguments)


class MPICHRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_process_count, process_per_node = _uniform_slot_counts(
            self.resource_pool, "MPICH")
        mpirun_cmd = [
            "mpirun", "-n", f"{total_process_count}", "-ppn",
            f"{process_per_node}",
        ]
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-genv", f"{k}={v}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + \
            list(self.user_arguments)


class IMPIRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_process_count, process_per_node = _uniform_slot_counts(
            self.resource_pool, "Intel MPI")
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-genv", f"{k}", f"{v}"]
        if self.args.bind_cores_to_rank:
            cores_per_rank = os.cpu_count() // process_per_node
            export_cmd += ["-genv", "OMP_NUM_THREADS", str(cores_per_rank)]
        export_cmd += ["-genv", "MASTER_ADDR", str(self.args.master_addr)]
        export_cmd += ["-genv", "MASTER_PORT", str(self.args.master_port)]
        export_cmd += ["-genv", "WORLD_SIZE", str(total_process_count)]
        export_cmd += ["-genv", "LOCAL_SIZE", str(process_per_node)]
        export_cmd += ["-hosts", ",".join(self.resource_pool.keys())]
        mpirun_cmd = ["mpirun", "-ppn", f"{process_per_node}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + \
            list(self.user_arguments)


class SlurmRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self) -> bool:
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment, active_resources):
        assert not getattr(self.args, "detect_nvlink_pairs", False), \
            "slurm backend does not support remapping visible devices"
        total_process_count = sum(len(v) for v in self.resource_pool.values())
        srun_cmd = [
            "srun", "-n", f"{total_process_count}",
        ]
        if getattr(self.args, "include", ""):
            srun_cmd.append(f"--include={self.args.include}")
        if getattr(self.args, "exclude", ""):
            srun_cmd.append(f"--exclude={self.args.exclude}")
        if getattr(self.args, "num_nodes", -1) > 0:
            srun_cmd.append(f"--nodes={self.args.num_nodes}")
        if getattr(self.args, "num_gpus", -1) > 0:
            srun_cmd.append(f"--gpus={self.args.num_gpus}")
        exports = ""
        for key, val in self.exports.items():
            exports += f",{key}={val}"
        python_exec = [sys.executable, "-u"]
        command = srun_cmd + [f"--export=ALL{exports}"] + python_exec + \
            [self.user_script] + list(self.user_arguments)
        return command


class MVAPICHRunner(MultiNodeRunner):
    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("MV2_SMP_USE_CMA", "0")
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")

    def backend_exists(self) -> bool:
        mpiname = shutil.which("mpiname")
        if mpiname is None:
            return False
        try:
            import subprocess

            out = subprocess.check_output(["mpiname"], text=True)
            return "MVAPICH2-GDR" in out
        except Exception:
            return False

    def get_cmd(self, environment, active_resources):
        total_process_count, process_per_node = _uniform_slot_counts(
            self.resource_pool, "MVAPICH")
        with open(".mvapich_hostfile", "w") as f:
            for host in self.resource_pool.keys():
                f.write(f"{host}\n")
        mpirun_cmd = [
            "mpirun", "-np", f"{total_process_count}", "-ppn",
            f"{process_per_node}", "--hostfile", ".mvapich_hostfile",
        ]
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-env", f"{k}={v}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + \
            list(self.user_arguments)
