"""Accelerator abstraction seam.

Capability parity with the reference's ``accelerator/abstract_accelerator.py:10
DeepSpeedAccelerator`` ABC — device naming, memory stats, RNG, synchronization,
communication-backend name — re-expressed for JAX backends. The seam exists so
offload code and the test harness run unchanged on a CPU host without TPUs
(reference motivation: accelerator/real_accelerator.py:45).

Streams/events have no user-visible analog under XLA (the compiler schedules
async ops); the matching surface here is async dispatch + ``synchronize`` =
``block_until_ready``.
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional


class Accelerator(abc.ABC):
    _name: str = "abstract"
    _communication_backend_name: str = "abstract"

    # --- identity ---
    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def is_available(self) -> bool:
        return self.device_count() > 0

    @abc.abstractmethod
    def devices(self) -> List[Any]:
        ...

    def device_count(self) -> int:
        return len(self.devices())

    def local_devices(self) -> List[Any]:
        import jax

        return [d for d in self.devices() if d.process_index == jax.process_index()]

    def current_device(self):
        return self.devices()[0]

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # --- synchronization (streams/events ≅ async dispatch under XLA) ---
    def synchronize(self, tensors=None) -> None:
        import jax

        if tensors is not None:
            jax.block_until_ready(tensors)
        else:
            import numpy as np

            # A tiny device round-trip drains the dispatch queue on all local
            # devices, standing in for torch.cuda.synchronize().
            for d in self.local_devices():
                jax.block_until_ready(jax.device_put(np.zeros(()), d))

    # --- RNG ---
    def default_generator(self, seed: int):
        import jax

        return jax.random.PRNGKey(seed)

    # --- memory ---
    def memory_stats(self, device=None) -> dict:
        dev = device if device is not None else self.current_device()
        try:
            return dict(dev.memory_stats() or {})
        except Exception:
            return {}

    def memory_allocated(self, device=None) -> int:
        return int(self.memory_stats(device).get("bytes_in_use", 0))

    def max_memory_allocated(self, device=None) -> int:
        return int(self.memory_stats(device).get("peak_bytes_in_use", 0))

    def total_memory(self, device=None) -> int:
        return int(self.memory_stats(device).get("bytes_limit", 0))

    def available_memory(self, device=None) -> int:
        stats = self.memory_stats(device)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    # --- dtypes ---
    def supported_dtypes(self):
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    # --- tracing ranges (NVTX analog; surfaced to jax profiler) ---
    def range_push(self, msg: str):
        import jax.profiler

        tc = jax.profiler.TraceAnnotation(msg)
        tc.__enter__()
        self._range_stack = getattr(self, "_range_stack", [])
        self._range_stack.append(tc)

    def range_pop(self):
        stack = getattr(self, "_range_stack", [])
        if stack:
            stack.pop().__exit__(None, None, None)

    def on_accelerator(self, array) -> bool:
        try:
            return any(d in self.devices() for d in array.devices())
        except AttributeError:
            return False
