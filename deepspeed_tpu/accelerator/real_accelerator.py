"""Accelerator selection (≅ reference ``accelerator/real_accelerator.py:45``).

Selection order: ``DSTPU_ACCELERATOR`` env override, else the platform of
``jax.devices()`` (tpu → TpuAccelerator, gpu → GpuAccelerator, otherwise
CpuAccelerator).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .abstract_accelerator import Accelerator

_accelerator: Optional[Accelerator] = None


class TpuAccelerator(Accelerator):
    _name = "tpu"
    _communication_backend_name = "ici"

    def devices(self) -> List:
        import jax

        return jax.devices("tpu")

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16


class GpuAccelerator(Accelerator):
    _name = "gpu"
    _communication_backend_name = "nccl"

    def devices(self) -> List:
        import jax

        return jax.devices("gpu")


class CpuAccelerator(Accelerator):
    _name = "cpu"
    _communication_backend_name = "gloo"

    def devices(self) -> List:
        import jax

        return jax.devices("cpu")

    def memory_stats(self, device=None) -> dict:
        try:
            import psutil

            vm = psutil.virtual_memory()
            return {"bytes_in_use": vm.used, "bytes_limit": vm.total,
                    "peak_bytes_in_use": vm.used}
        except Exception:
            return {}


_ACCELERATORS = {"tpu": TpuAccelerator, "gpu": GpuAccelerator, "cpu": CpuAccelerator}


def get_accelerator() -> Accelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    name = os.environ.get("DSTPU_ACCELERATOR", "").lower() or None
    if name is None:
        import jax

        platform = jax.default_backend()
        name = platform if platform in _ACCELERATORS else "cpu"
    _accelerator = _ACCELERATORS[name]()
    return _accelerator


def set_accelerator(accel: Accelerator) -> None:
    global _accelerator
    _accelerator = accel
