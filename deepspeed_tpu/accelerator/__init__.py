from .abstract_accelerator import Accelerator  # noqa: F401
from .real_accelerator import (  # noqa: F401
    CpuAccelerator,
    GpuAccelerator,
    TpuAccelerator,
    get_accelerator,
    set_accelerator,
)
