"""3D (pp × tp × dp) reshape descriptor.

Capability parity with reference ``deepspeed/checkpoint/reshape_3d_utils.py``
(:17 ``model_3d_desc``, :73 ``get_model_3d_descriptor``) — describes a 3D
checkpoint layout and computes, for each coordinate of a (smaller) target
layout, the source ranks whose shards must merge.
"""

from __future__ import annotations

import os
import re
from typing import List, Tuple

from .reshape_meg_2d import meg_2d_parallel_map, reshape_meg_2d_parallel
from .reshape_utils import get_files, get_files_with_prefix

PP_DIM = "PP"
TP_DIM = "TP"
DP_DIM = "DP"

MODEL_FILE_PREFIX = "mp_rank_"
LAYER_FILE_PREFIX = "layer_"
ZERO_FILE_PREFIX = "zero_pp_rank_"


def get_zero_files(dir_: str) -> List[str]:
    return get_files_with_prefix(get_files(dir_), ZERO_FILE_PREFIX)


class model_3d_desc:
    def __init__(self, pp_degree: int = 1, tp_degree: int = 1,
                 dp_degree: int = 1):
        self.pp_degree = pp_degree
        self.tp_degree = tp_degree
        self.dp_degree = dp_degree

    def reshape(self, target_3d_desc: "model_3d_desc",
                verbose: bool = False) -> List[Tuple]:
        valid_reshape, reshape_errors = self.can_reshape(target_3d_desc)
        assert valid_reshape, ",".join(reshape_errors)
        tgt_2d_map = reshape_meg_2d_parallel(
            old_pp_degree=self.pp_degree, old_tp_degree=self.tp_degree,
            new_pp_degree=target_3d_desc.pp_degree,
            new_tp_degree=target_3d_desc.tp_degree, verbose=verbose)
        flat_3d_map = _flatten_dp_dimension(
            tgt_2d_map, self.pp_degree * self.tp_degree, self.dp_degree)
        return _unflatten_dp_dimension(flat_3d_map, target_3d_desc.dp_degree)

    def get_desc(self) -> str:
        return (f"{PP_DIM},{TP_DIM},{DP_DIM} = ({self.pp_degree}, "
                f"{self.tp_degree}, {self.dp_degree})")

    def world_size(self) -> int:
        return self.pp_degree * self.tp_degree * self.dp_degree

    def is_valid(self, pp_index: int, tp_index: int, dp_index: int):
        err_msg = []
        for index, degree, dim_name in [(pp_index, self.pp_degree, PP_DIM),
                                        (tp_index, self.tp_degree, TP_DIM),
                                        (dp_index, self.dp_degree, DP_DIM)]:
            if index >= degree:
                err_msg.append(f"{dim_name} indexing error: index {index} "
                               f">= degree {degree}")
        return len(err_msg) == 0, err_msg

    def can_reshape(self, target_3d_desc: "model_3d_desc"):
        err_msg = []
        for dim_name, old, new in [
                (PP_DIM, self.pp_degree, target_3d_desc.pp_degree),
                (TP_DIM, self.tp_degree, target_3d_desc.tp_degree),
                (DP_DIM, self.dp_degree, target_3d_desc.dp_degree)]:
            if new > old:
                err_msg.append(f"Expansion reshape not supported - "
                               f"{dim_name}: {old} ---> {new}")
        return len(err_msg) == 0, err_msg


def get_model_3d_descriptor(dir_: str) -> model_3d_desc:
    """Infer (pp, tp, dp) from a checkpoint dir's file naming — reference
    reshape_3d_utils.py:73. Works on both reference-format dirs (layer_XX /
    mp_rank_XX .pt) and this framework's dirs."""
    file_list = get_files(dir_)
    zero_file_list = get_zero_files(dir_)
    num_pp0_files = len(get_files_with_prefix(file_list,
                                              f"{LAYER_FILE_PREFIX}01"))
    if num_pp0_files > 0:
        tp_degree = num_pp0_files
        pp_degree = len(get_files_with_prefix(
            file_list, MODEL_FILE_PREFIX)) // tp_degree
        dp_degree = max(1, len(zero_file_list) // (pp_degree * tp_degree))
    else:
        tp_degree = len(get_files_with_prefix(file_list, MODEL_FILE_PREFIX))
        dp_degree = max(1, len(zero_file_list) // max(tp_degree, 1))
        pp_degree = 0
    return model_3d_desc(pp_degree, tp_degree, dp_degree)


def _flatten_dp_dimension(meg_2d_map: meg_2d_parallel_map, src_2d_size: int,
                          dp_degree: int) -> meg_2d_parallel_map:
    new_map = meg_2d_parallel_map(meg_2d_map.pp_degree, meg_2d_map.tp_degree)
    for pp_index in range(meg_2d_map.pp_degree):
        for tp_index in range(meg_2d_map.tp_degree):
            dp0_indices = meg_2d_map.get_data(pp_index, tp_index)
            for idx in dp0_indices:
                new_map.add_data(pp_index, tp_index,
                                 [idx + i * src_2d_size
                                  for i in range(dp_degree)])
    return new_map


def _unflatten_dp_dimension(meg_2d_map: meg_2d_parallel_map,
                            dp_degree: int) -> List[meg_2d_parallel_map]:
    """Split each coordinate's flat rank list into dp_degree maps."""
    dp_maps = [meg_2d_parallel_map(meg_2d_map.pp_degree,
                                   meg_2d_map.tp_degree)
               for _ in range(dp_degree)]
    for key, ranks in meg_2d_map.map.items():
        pp_index, tp_index = map(int, key.split(","))
        assert len(ranks) % dp_degree == 0
        chunk = len(ranks) // dp_degree
        for dp_index in range(dp_degree):
            dp_maps[dp_index].add_data(
                pp_index, tp_index,
                ranks[dp_index * chunk:(dp_index + 1) * chunk])
    return dp_maps
