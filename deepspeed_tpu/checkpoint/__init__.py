from .deepspeed_checkpoint import DeepSpeedCheckpoint
from .gpt2_import import megatron_gpt2_to_flax
from .reshape_3d_utils import get_model_3d_descriptor, model_3d_desc
from .reshape_meg_2d import meg_2d_parallel_map, reshape_meg_2d_parallel
from .universal_checkpoint import ds_to_universal, load_universal, universal_dir
from .zero_to_fp32 import (
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint,
)

__all__ = [
    "ds_to_universal", "load_universal", "universal_dir",
    "get_fp32_state_dict_from_zero_checkpoint",
    "convert_zero_checkpoint_to_fp32_state_dict",
    "DeepSpeedCheckpoint", "meg_2d_parallel_map", "reshape_meg_2d_parallel",
    "model_3d_desc", "get_model_3d_descriptor", "megatron_gpt2_to_flax",
]
