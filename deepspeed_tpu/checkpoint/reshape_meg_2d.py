"""2D (pipeline × tensor) parallel reshape maps.

Capability parity with reference ``deepspeed/checkpoint/reshape_meg_2d.py:80
reshape_meg_2d_parallel`` — computes, for each (pp, tp) coordinate of a NEW
parallel layout, which OLD ranks' checkpoint shards it must merge. Used by
the offline reshaper and by universal-checkpoint loading of 3D layouts.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .reshape_utils import partition_data


class meg_2d_parallel_map:
    def __init__(self, pp_degree: int, tp_degree: int):
        self.pp_degree = pp_degree
        self.tp_degree = tp_degree
        self.map: Dict[str, List[int]] = {}

    def simple_init(self) -> None:
        # rank layout: tp fastest-varying within pp (Megatron convention)
        self.map = {
            self._make_key(i // self.tp_degree, i % self.tp_degree): [i]
            for i in range(self.pp_degree * self.tp_degree)
        }

    def add_data(self, pp_index: int, tp_index: int, data: List[int]) -> None:
        self._validate_indices(pp_index, tp_index)
        assert isinstance(data, list)
        key = self._make_key(pp_index, tp_index)
        self.map.setdefault(key, [])
        self.map[key] += data

    def get_data(self, pp_index: Optional[int] = None,
                 tp_index: Optional[int] = None) -> List[int]:
        self._validate_indices(pp_index, tp_index)
        pp_indices = range(self.pp_degree) if pp_index is None else [pp_index]
        tp_indices = range(self.tp_degree) if tp_index is None else [tp_index]
        result: List[int] = []
        for i in pp_indices:
            for j in tp_indices:
                result += self.map[self._make_key(i, j)]
        return result

    def print_data(self, tag: str) -> None:
        print(tag)
        for key, value in self.map.items():
            print(f"{key} = {value}")

    def _validate_indices(self, pp_index, tp_index) -> None:
        assert pp_index is None or pp_index < self.pp_degree
        assert tp_index is None or tp_index < self.tp_degree

    @staticmethod
    def _make_key(i: int, j: int) -> str:
        return f"{i},{j}"


def _reshape_tp_dimension(old_2d_map: meg_2d_parallel_map,
                          new_tp_degree: int) -> meg_2d_parallel_map:
    new_map = meg_2d_parallel_map(old_2d_map.pp_degree, new_tp_degree)
    for i in range(old_2d_map.pp_degree):
        ranks = old_2d_map.get_data(pp_index=i, tp_index=None)
        for j, split in enumerate(partition_data(ranks, new_tp_degree)):
            new_map.add_data(i, j, split)
    return new_map


def _reshape_pp_dimension(old_2d_map: meg_2d_parallel_map,
                          new_pp_degree: int) -> meg_2d_parallel_map:
    new_map = meg_2d_parallel_map(new_pp_degree, old_2d_map.tp_degree)
    for i in range(old_2d_map.tp_degree):
        ranks = old_2d_map.get_data(pp_index=None, tp_index=i)
        for j, split in enumerate(partition_data(ranks, new_pp_degree)):
            new_map.add_data(j, i, split)
    return new_map


def reshape_meg_2d_parallel(old_pp_degree: int, old_tp_degree: int,
                            new_pp_degree: int, new_tp_degree: int,
                            verbose: bool = False) -> meg_2d_parallel_map:
    assert new_pp_degree <= old_pp_degree, "pp can only shrink in a reshape"
    assert new_tp_degree <= old_tp_degree, "tp can only shrink in a reshape"
    old_2d_map = meg_2d_parallel_map(old_pp_degree, old_tp_degree)
    old_2d_map.simple_init()
    if verbose:
        old_2d_map.print_data("original_2d_map:")
    new_map = old_2d_map
    if old_tp_degree != new_tp_degree:
        new_map = _reshape_tp_dimension(new_map, new_tp_degree)
    if verbose and new_map is not old_2d_map:
        new_map.print_data("after_tp_reshape:")
    if old_pp_degree != new_pp_degree:
        new_map = _reshape_pp_dimension(new_map, new_pp_degree)
    if verbose:
        new_map.print_data("final_2d_map:")
    return new_map


def get_mpu_ranks(tp_size: int = 1, pp_size: int = 1, dp_size: int = 1):
    """Enumerate the (tp, pp, dp) rank groups of a world of
    tp*pp*dp ranks laid out Megatron-style (tp fastest, then pp, then dp).
    Returns (tp_groups, pp_groups, dp_groups) as rank lists."""
    world = tp_size * pp_size * dp_size
    tp_groups = [list(range(i, i + tp_size))
                 for i in range(0, world, tp_size)]
    num_pp_groups = world // pp_size
    pp_groups = []
    for i in range(num_pp_groups):
        ranks = list(range(i, world, num_pp_groups))
        pp_groups.append(ranks)
    dp_groups = []
    ranks_per_pp = world // pp_size
    for i in range(pp_size):
        start = i * ranks_per_pp
        for j in range(tp_size):
            dp_groups.append(list(range(start + j, start + ranks_per_pp,
                                        tp_size)))
    return tp_groups, pp_groups, dp_groups
