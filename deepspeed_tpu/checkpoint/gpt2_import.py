"""Import a reference-format (Megatron-DeepSpeed) GPT-2 checkpoint into
this framework's flax GPT-2 parameter tree.

The migration counterpart of the reference's inference-time resharding
(``runtime/state_dict_factory.py:190 MegatronSDLoader``) and AutoTP weight
placement (``module_inject/auto_tp.py:13``): :class:`DeepSpeedCheckpoint`
merges the TP/PP layer-file shards (qkv/row/col concat rules,
deepspeed_checkpoint.get_layer_cat_dim), and this module renames + re-lays
the merged torch tensors into ``models/gpt2.GPT2LMHeadModel``'s tree:

* torch Linear weights are (out, in) → flax kernels (in, out): transpose;
* ``query_key_value`` keeps the contiguous [q|k|v] layout (checkpoint
  version 0 — matching models/gpt2's ``jnp.split(qkv, 3, axis=-1)``);
* per-layer dicts stack into the (n_layer, ...) leaves of the nn.scan'd
  block (metadata axis 0);
* Megatron pads the vocab for TP divisibility — rows beyond
  ``config.vocab_size`` are sliced off.

Logits parity of the imported tree (tp=2 shards vs unsharded) is asserted
in tests/unit/checkpoint/test_gpt2_import.py.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .deepspeed_checkpoint import DeepSpeedCheckpoint

# Megatron layer-file param name -> (our path inside a block, transpose?)
_BLOCK_MAP = {
    "input_layernorm.weight": (("ln_1", "scale"), False),
    "input_layernorm.bias": (("ln_1", "bias"), False),
    "self_attention.query_key_value.weight": (("attn", "qkv", "kernel"), True),
    "self_attention.query_key_value.bias": (("attn", "qkv", "bias"), False),
    "self_attention.dense.weight": (("attn", "proj", "kernel"), True),
    "self_attention.dense.bias": (("attn", "proj", "bias"), False),
    # pre-SelfAttention-rename Megatron checkpoints
    "attention.query_key_value.weight": (("attn", "qkv", "kernel"), True),
    "attention.query_key_value.bias": (("attn", "qkv", "bias"), False),
    "attention.dense.weight": (("attn", "proj", "kernel"), True),
    "attention.dense.bias": (("attn", "proj", "bias"), False),
    "post_attention_layernorm.weight": (("ln_2", "scale"), False),
    "post_attention_layernorm.bias": (("ln_2", "bias"), False),
    "mlp.dense_h_to_4h.weight": (("mlp", "fc", "kernel"), True),
    "mlp.dense_h_to_4h.bias": (("mlp", "fc", "bias"), False),
    "mlp.dense_4h_to_h.weight": (("mlp", "proj", "kernel"), True),
    "mlp.dense_4h_to_h.bias": (("mlp", "proj", "bias"), False),
}


def _set(tree: Dict, path, value) -> None:
    for p in path[:-1]:
        tree = tree.setdefault(p, {})
    tree[path[-1]] = value


def megatron_gpt2_to_flax(ckpt_dir: str, config) -> Dict[str, Any]:
    """Read a Megatron-DeepSpeed GPT-2 layer-file checkpoint (any original
    TP/PP degree) and return the full (unsharded) flax param tree for
    ``GPT2LMHeadModel(config)``. Shard it onto any mesh with
    ``gpt2_sharding_rules`` / ``ds.initialize(model_parameters=...)``."""
    ckpt = DeepSpeedCheckpoint(ckpt_dir, tp_degree=1, pp_degree=1)
    version = ckpt.checkpoint_version()
    if version >= 1.0:
        raise NotImplementedError(
            f"checkpoint_version {version}: versions >= 1.0 store qkv "
            f"per-head-interleaved, which does not match this model's "
            f"contiguous [q|k|v] split — re-layout support is not "
            f"implemented; convert with Megatron's own tools first")
    params: Dict[str, Any] = {}

    emb = ckpt.get_embedding_state(0)
    wte = np.asarray(emb["word_embeddings.weight"])[:config.vocab_size]
    _set(params, ("wte", "embedding"), wte)
    if "position_embeddings.weight" in emb:
        _set(params, ("wpe", "embedding"),
             np.asarray(emb["position_embeddings.weight"])
             [:config.n_positions])

    norm = ckpt.get_final_norm_state(0)
    _set(params, ("ln_f", "scale"), np.asarray(norm["weight"]))
    if "bias" in norm:
        _set(params, ("ln_f", "bias"), np.asarray(norm["bias"]))

    # one merged state dict per transformer layer: the per-layer files are
    # one-per-original-tp-rank; merge with the qkv/row/col concat rules
    # (same path to_universal takes)
    layers: List[Dict[str, Any]] = [
        ckpt.merged_layer_state(layer_key)
        for layer_key in ckpt.layer_keys[1:-1]]
    assert len(layers) == config.n_layer, \
        f"checkpoint has {len(layers)} transformer layers, config wants " \
        f"{config.n_layer}"
    stacked: Dict[tuple, List[np.ndarray]] = {}
    for sd in layers:
        for name, value in sd.items():
            if name not in _BLOCK_MAP:
                continue
            path, transpose = _BLOCK_MAP[name]
            arr = np.asarray(value)
            if transpose:
                arr = arr.T
            stacked.setdefault(path, []).append(arr)
    for path, arrs in stacked.items():
        assert len(arrs) == config.n_layer, \
            f"param {'/'.join(path)} present in only {len(arrs)} layers"
        _set(params, ("blocks", "block") + path, np.stack(arrs))
    return params
