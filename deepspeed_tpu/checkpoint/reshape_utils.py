"""Checkpoint reshape primitives.

Capability parity with reference ``deepspeed/checkpoint/reshape_utils.py`` —
rank-list partitioning and state-dict merge helpers used by the 2D/3D
reshape maps.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

import numpy as np


def basic_folder_validation(dir_: str) -> None:
    assert os.path.exists(dir_), f"{dir_} path does not exist"
    assert os.path.isdir(dir_), f"{dir_} is not a folder"


def get_files_with_prefix(all_files: List[str], prefix: str) -> List[str]:
    return sorted(f for f in all_files if os.path.basename(f).startswith(prefix))


def get_files(dir_: str) -> List[str]:
    file_list = []
    for root, _, files in os.walk(dir_):
        for file in files:
            file_list.append(os.path.join(root, file))
    return file_list


def partition_data(data_list: List[Any], num_partitions: int) -> List[List[Any]]:
    """Split a list into equal contiguous partitions."""
    num_elems = len(data_list)
    assert num_elems % num_partitions == 0, \
        f"cannot partition {num_elems} items into {num_partitions}"
    partition_size = num_elems // num_partitions
    return [data_list[i * partition_size:(i + 1) * partition_size]
            for i in range(num_partitions)]


def merge_state_dicts(sd_list: List[Dict[str, Any]],
                      cat_dim_fn=None) -> Dict[str, Any]:
    """Merge per-TP-rank state dicts: arrays concatenate on their slicing
    dim (``cat_dim_fn(key) -> int | None``; None = must be replicated)."""
    merged: Dict[str, Any] = {}
    for key in sd_list[0]:
        values = [sd[key] for sd in sd_list]
        dim = cat_dim_fn(key) if cat_dim_fn else None
        if dim is None or np.ndim(values[0]) == 0:
            merged[key] = values[0]
        else:
            merged[key] = np.concatenate([np.asarray(v) for v in values],
                                         axis=dim)
    return merged
