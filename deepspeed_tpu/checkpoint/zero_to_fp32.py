"""ZeRO-checkpoint → consolidated fp32 state dict.

Capability parity with reference ``deepspeed/utils/zero_to_fp32.py``
(:459 ``get_fp32_state_dict_from_zero_checkpoint``, :508 CLI) — the script
the reference auto-copies into every checkpoint dir (engine.py:3227) so
users can extract framework-free weights.

The TPU checkpoints store whole logical arrays (GSPMD handled the physical
sharding), so consolidation is a read + upcast rather than a flat-buffer
reassembly; the user-facing function and CLI match the reference.
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Dict, Optional

import numpy as np

from ..runtime.checkpoint_engine.checkpoint_engine import (
    ArrayCheckpointEngine,
    checkpoint_meta_path,
    read_latest,
)
from ..utils.logging import logger
from .universal_checkpoint import _flatten, _unflatten


def get_fp32_state_dict_from_zero_checkpoint(
        checkpoint_dir: str, tag: Optional[str] = None,
        flat_keys: bool = True) -> Dict[str, np.ndarray]:
    """Returns ``{param_name: fp32 ndarray}`` from a checkpoint dir —
    reference zero_to_fp32.py:459. Prefers the fp32 master weights; falls
    back to upcasting the compute-dtype module params."""
    if tag is None:
        tag = read_latest(checkpoint_dir)
    engine = ArrayCheckpointEngine()
    sd = engine.load(checkpoint_meta_path(checkpoint_dir, tag, "model",
                                          mp_rank=0, dp_rank=0))
    master = sd.get("master")
    if not master and sd.get("offload_optimizer"):
        master = sd["offload_optimizer"].get("master")
    source = master if master else sd["module"]
    # offload masters are stored flat with "/"-joined paths; normalize to "."
    tree = {k.replace("/", "."): np.asarray(v, dtype=np.float32)
            for k, v in _flatten(source).items() if v is not None}
    if flat_keys:
        return tree
    return _unflatten({k.replace(".", "/"): v for k, v in tree.items()})


def convert_zero_checkpoint_to_fp32_state_dict(
        checkpoint_dir: str, output_file: str,
        tag: Optional[str] = None) -> None:
    """Write the consolidated fp32 state dict to ``output_file`` (.npz) —
    reference zero_to_fp32.py:508 writes a torch file; here it is an npz
    keyed by dotted param names, loadable with numpy alone."""
    state_dict = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    np.savez(output_file, **state_dict)
    total = sum(v.size for v in state_dict.values())
    logger.info(f"saved {len(state_dict)} params ({total / 1e6:.1f}M elems) "
                f"to {output_file}")


def main():
    parser = argparse.ArgumentParser(
        description="Extract fp32 weights from a DeepSpeed-TPU checkpoint")
    parser.add_argument("checkpoint_dir", type=str,
                        help="checkpoint dir containing the 'latest' file")
    parser.add_argument("output_file", type=str,
                        help="output .npz path for the fp32 state dict")
    parser.add_argument("-t", "--tag", type=str, default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
