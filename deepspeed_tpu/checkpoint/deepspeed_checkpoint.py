"""Reader / reshaper for reference-format (Megatron-DeepSpeed) checkpoints.

Capability parity with reference
``deepspeed/checkpoint/deepspeed_checkpoint.py:33 DeepSpeedCheckpoint`` — an
abstraction over a 3D (tp, pp, dp) checkpoint directory: degree discovery,
per-layer file maps, tp-merge of embedding/transformer/final-norm states.
Doubles as the **migration path** from the reference framework: it reads
torch ``.pt`` checkpoint dirs (torch is available CPU-only) and can emit
this framework's universal format via :func:`to_universal`, after which
``engine.load_universal_checkpoint`` restores at any TPU mesh layout.

TP merge heuristics (reference state_dict_factory.py:190 MegatronSDLoader):
column-parallel params (qkv, mlp up / h_to_4h) concatenate on dim 0;
row-parallel (attention output dense, mlp down / 4h_to_h) on dim 1;
everything else must be replicated and takes rank 0's copy.
"""

from __future__ import annotations

import json
import os
import pickle
import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import logger
from .reshape_3d_utils import (
    LAYER_FILE_PREFIX,
    MODEL_FILE_PREFIX,
    get_model_3d_descriptor,
)
from .reshape_utils import (
    basic_folder_validation,
    get_files,
    get_files_with_prefix,
    merge_state_dicts,
    partition_data,
)

EMBEDDING_LAYER_INDEX = 0
FINAL_LAYER_NORM_INDEX = -1
ARGS_KEY = "args"
CHECKPOINT_INFO_KEY = "checkpoint_info"
ITERATION_KEY = "iteration"

SEQUENTIAL_LAYERS = [
    "input_layernorm.weight", "input_layernorm.bias",
    "self_attention.dense.bias", "attention.dense.bias",
    "post_attention_layernorm.weight", "post_attention_layernorm.bias",
    "mlp.dense_4h_to_h.bias", "position_embeddings.weight",
]
# param-name suffix → concat dim for TP merge
LAYER_CONCAT_DIM = {
    "self_attention.dense.weight": 1,
    "attention.dense.weight": 1,
    "mlp.dense_4h_to_h.weight": 1,
}
_DEFAULT_COL_PARALLEL_DIM = 0


def _to_numpy(value):
    if hasattr(value, "detach"):  # torch tensor
        t = value.detach().cpu()
        if t.dtype.is_floating_point and t.element_size() == 2 \
                and "bfloat16" in str(t.dtype):
            t = t.float()
        return t.numpy()
    return np.asarray(value)


def _torch_load(path: str) -> Dict[str, Any]:
    """Load a reference-format .pt checkpoint.

    Prefers ``weights_only=True`` (no arbitrary-code unpickling) with the
    Megatron ``args`` Namespace allowlisted; only on failure falls back to
    full unpickling, which EXECUTES code embedded in the file — reference
    checkpoints routinely carry custom classes, but only fall through for
    files you trust.
    """
    import argparse

    import torch

    try:
        if hasattr(torch.serialization, "add_safe_globals"):
            torch.serialization.add_safe_globals([argparse.Namespace])
        return torch.load(path, map_location="cpu", weights_only=True)
    except TypeError:
        # torch < 1.13: no weights_only kwarg — plain load, as before
        return torch.load(path, map_location="cpu")
    except (pickle.UnpicklingError, RuntimeError) as e:
        # torch raises UnpicklingError on some versions, RuntimeError on
        # others, for weights_only failures (OSError/FileNotFoundError pass
        # through unchanged); the unsafe fallback requires explicit opt-in.
        # Unrelated RuntimeErrors (e.g. a truncated zip) propagate as-is —
        # retrying them unsafely is futile and the opt-in hint misleading.
        msg = str(e)
        if isinstance(e, RuntimeError) and \
                "Weights only load failed" not in msg and \
                "Unsupported global" not in msg and \
                "weights_only" not in msg:
            raise
        if os.environ.get("DS_TRUST_CHECKPOINT") != "1":
            raise RuntimeError(
                f"{path} failed the weights_only safe load ({e}). Full "
                "unpickling EXECUTES code embedded in the checkpoint; if you "
                "trust this file, set DS_TRUST_CHECKPOINT=1 to allow it."
            ) from e
        logger.warning(
            "%s failed the weights_only safe load (%s); DS_TRUST_CHECKPOINT=1 "
            "set — falling back to full unpickling, which EXECUTES code "
            "embedded in the checkpoint.", path, e)
        return torch.load(path, map_location="cpu", weights_only=False)


def get_layer_cat_dim(key: str) -> Optional[int]:
    """TP concat dim for a param name; None = replicated. Norm params and
    the known-replicated suffixes stay whole; row-parallel weights merge on
    dim 1; column-parallel weights AND their biases (qkv, h_to_4h,
    embeddings) merge on dim 0."""
    for suffix in SEQUENTIAL_LAYERS:
        if key.endswith(suffix):
            return None
    for suffix, dim in LAYER_CONCAT_DIM.items():
        if key.endswith(suffix):
            return dim
    if "layernorm" in key.lower() or ".norm." in key or \
            key.endswith("norm.weight") or key.endswith("norm.bias"):
        return None
    return _DEFAULT_COL_PARALLEL_DIM


class DeepSpeedCheckpoint:
    def __init__(self, dir: str, tp_degree: Optional[int] = None,
                 pp_degree: Optional[int] = None,
                 dp_degree: Optional[int] = None):
        self.dir = dir
        basic_folder_validation(dir)
        self.file_list = get_files(dir)
        self.layer_files = get_files_with_prefix(self.file_list,
                                                 LAYER_FILE_PREFIX)
        self.mp_rank_files = get_files_with_prefix(self.file_list,
                                                   MODEL_FILE_PREFIX)
        self.layer_keys = self._get_layer_keys()

        src = get_model_3d_descriptor(dir)
        self.zero_checkpoint_desc = src
        self.original_tp_degree = src.tp_degree
        self.original_pp_degree = max(src.pp_degree, 1)
        self.original_dp_degree = src.dp_degree
        self.tp_degree = tp_degree if tp_degree is not None \
            else self.original_tp_degree
        self.pp_degree = pp_degree if pp_degree is not None \
            else self.original_pp_degree
        self.dp_degree = dp_degree if dp_degree is not None \
            else self.original_dp_degree
        self.global_state: Dict[str, Any] = {}

        self.tp_to_embedding_map = self._build_tp_other_layer_map(
            EMBEDDING_LAYER_INDEX)
        self.tp_to_final_norm_map = self._build_tp_other_layer_map(
            FINAL_LAYER_NORM_INDEX)
        self.pp_to_transformer_map = self._build_pp_transformer_map()
        self.transformer_file_map = self._build_transformer_file_map()

    # -- degree queries ---------------------------------------------------
    def is_change_tp_degree(self) -> bool:
        return self.tp_degree != self.original_tp_degree

    def is_change_pp_degree(self) -> bool:
        return self.pp_degree != self.original_pp_degree

    def is_change_dp_degree(self) -> bool:
        return self.dp_degree != self.original_dp_degree

    # -- mapping construction ---------------------------------------------
    def _get_layer_keys(self) -> List[str]:
        key_set = set()
        for file_path in self.layer_files:
            m = re.search(rf"{LAYER_FILE_PREFIX}(\d+)",
                          os.path.basename(file_path))
            if m:
                key_set.add(m.group(1))
        return sorted(key_set, key=int)

    def _build_tp_other_layer_map(self, layer_index: int) -> Dict[int, List[str]]:
        if not self.layer_keys:
            return {}
        layer_key = self.layer_keys[layer_index]
        layer_files = get_files_with_prefix(
            self.layer_files, f"{LAYER_FILE_PREFIX}{layer_key}")
        partitions = partition_data(layer_files, self.tp_degree)
        return {i: partitions[i] for i in range(self.tp_degree)}

    def _build_pp_transformer_map(self) -> Dict[int, List[str]]:
        if not self.layer_keys:
            return {}
        transformer_keys = self.layer_keys[1:-1]
        # contiguous split covering every layer (early stages take the
        # remainder) — a floor split would silently drop trailing layers
        n = len(transformer_keys)
        base, rem = divmod(n, self.pp_degree)
        out: Dict[int, List[str]] = {}
        start = 0
        for i in range(self.pp_degree):
            count = base + (1 if i < rem else 0)
            out[i] = transformer_keys[start:start + count]
            start += count
        return out

    def _build_transformer_file_map(self) -> Dict[tuple, List[str]]:
        file_map: Dict[tuple, List[str]] = {}
        for pp_index, layer_keys in self.pp_to_transformer_map.items():
            for layer_key in layer_keys:
                layer_files = get_files_with_prefix(
                    self.layer_files, f"{LAYER_FILE_PREFIX}{layer_key}")
                partitions = partition_data(layer_files, self.tp_degree)
                for tp_index in range(self.tp_degree):
                    file_map.setdefault((tp_index, pp_index), [])
                    file_map[(tp_index, pp_index)] += partitions[tp_index]
        return file_map

    # -- state access -----------------------------------------------------
    def _merge_tp_files(self, files: List[str]) -> Dict[str, np.ndarray]:
        sds = [{k: _to_numpy(v) for k, v in _torch_load(f).items()
                if not k.startswith("_")} for f in files]
        if len(sds) == 1:
            return sds[0]
        return merge_state_dicts(sds, cat_dim_fn=get_layer_cat_dim)

    def get_embedding_state(self, tp_index: int) -> Dict[str, np.ndarray]:
        assert tp_index in self.tp_to_embedding_map
        return self._merge_tp_files(self.tp_to_embedding_map[tp_index]) \
            if len(self.tp_to_embedding_map[tp_index]) > 1 else \
            {k: _to_numpy(v)
             for k, v in _torch_load(self.tp_to_embedding_map[tp_index][0]).items()}

    def get_embedding_files(self, tp_index: int) -> List[str]:
        return self.tp_to_embedding_map[tp_index]

    def get_final_norm_state(self, tp_index: int) -> Dict[str, np.ndarray]:
        return {k: _to_numpy(v)
                for k, v in _torch_load(
                    self.tp_to_final_norm_map[tp_index][0]).items()}

    def get_final_norm_files(self, tp_index: int) -> List[str]:
        return self.tp_to_final_norm_map[tp_index]

    def get_transformer_state(self, tp_index: int,
                              pp_index: int) -> List[Dict[str, np.ndarray]]:
        t_list = []
        for fname in self.transformer_file_map[(tp_index, pp_index)]:
            sd = _torch_load(fname)
            t_list.append({k: _to_numpy(v) for k, v in sd.items()})
        return t_list

    def checkpoint_version(self) -> float:
        """Megatron checkpoint_version from the mp_rank state (0.0 when
        absent) — decides the qkv shard layout (state_dict_factory.py)."""
        sd = self._load_mp_rank_sd()
        v = sd.get("checkpoint_version", 0.0)
        try:
            return float(v)
        except (TypeError, ValueError):
            return 0.0

    def merged_layer_state(self, layer_key: str,
                           ckpt_version: Optional[float] = None
                           ) -> Dict[str, np.ndarray]:
        """One layer's state with ALL original-tp shards merged whole
        (qkv/row/col concat rules) — the building block of to_universal and
        of model importers.

        ``query_key_value`` params get the version-aware regroup
        (MegatronSDLoader.merge_query_key_value, reference
        state_dict_factory.py:220): version-0 shards store [q_r|k_r|v_r]
        fused per rank, so a naive dim-0 concat would interleave ranks'
        q/k/v — each shard is split into thirds and re-concatenated per
        component; versions >= 1.0 concat plainly. The version defaults to
        the checkpoint's own ``checkpoint_version``."""
        if ckpt_version is None:
            ckpt_version = self.checkpoint_version()
        layer_files = get_files_with_prefix(
            self.layer_files, f"{LAYER_FILE_PREFIX}{layer_key}")
        parts = partition_data(layer_files, self.original_tp_degree)
        sds = [{k: _to_numpy(v) for k, v in _torch_load(fs[0]).items()}
               for fs in parts]
        if len(sds) == 1:
            return sds[0]
        merged = merge_state_dicts(sds, cat_dim_fn=get_layer_cat_dim)
        from ..runtime.state_dict_factory import MegatronSDLoader

        loader = MegatronSDLoader([], version=ckpt_version)
        for key in merged:
            if MegatronSDLoader._is_qkv(key):
                merged[key] = loader.merge_query_key_value(
                    [np.asarray(sd[key]) for sd in sds], dim=0)
        return merged

    def get_pp_transformer_map(self, pp_index: int) -> List[str]:
        return self.pp_to_transformer_map[pp_index]

    def get_2d_parallel_files(self, tp_index: int,
                              pp_index: int) -> List[str]:
        return self.transformer_file_map.get((tp_index, pp_index), [])

    def _load_mp_rank_sd(self, tp_index: int = 0) -> Dict[str, Any]:
        if not self.mp_rank_files:
            return {}
        return _torch_load(self.mp_rank_files[min(tp_index,
                                                  len(self.mp_rank_files) - 1)])

    def get_iteration(self) -> int:
        if ITERATION_KEY not in self.global_state:
            sd = self._load_mp_rank_sd()
            self.global_state[ITERATION_KEY] = sd.get(ITERATION_KEY, 0)
        return self.global_state[ITERATION_KEY]

    def get_args(self):
        if ARGS_KEY not in self.global_state:
            sd = self._load_mp_rank_sd()
            self.global_state[ARGS_KEY] = sd.get(ARGS_KEY)
        return self.global_state[ARGS_KEY]

    def get_checkpoint_info(self, info_key: str = CHECKPOINT_INFO_KEY):
        sd = self._load_mp_rank_sd()
        return sd.get(info_key)

    def validate_files(self) -> None:
        for file in self.file_list:
            if not os.path.isfile(file):
                raise FileNotFoundError(f"{file} is not existent")

    # -- migration --------------------------------------------------------
    def to_universal(self, output_dir: str, tag: str = "migrated") -> str:
        """Merge all TP/PP shards into whole arrays and write this
        framework's universal-checkpoint format; load with
        ``engine.load_universal_checkpoint`` at any mesh layout."""
        from .universal_checkpoint import _save_tree_npz, universal_dir

        merged: Dict[str, np.ndarray] = {}
        if self.layer_keys:
            for layer_key in self.layer_keys:
                sd = self.merged_layer_state(layer_key)
                for k, v in sd.items():
                    merged[f"layer_{layer_key}/{k.replace('.', '/')}"] = v
        else:
            sds = []
            for f in self.mp_rank_files:
                raw = _torch_load(f)
                raw = raw.get("module", raw)
                sds.append({k: _to_numpy(v) for k, v in raw.items()
                            if hasattr(v, "shape")})
            sd = sds[0] if len(sds) == 1 else \
                merge_state_dicts(sds, cat_dim_fn=get_layer_cat_dim)
            for k, v in sd.items():
                merged[k.replace(".", "/")] = v

        out = universal_dir(output_dir, tag)
        os.makedirs(out, exist_ok=True)
        fp32_index = _save_tree_npz(os.path.join(out, "fp32.npz"), merged)
        meta = {
            "tag": tag, "step": int(self.get_iteration()),
            "opt_step": int(self.get_iteration()),
            "global_steps": int(self.get_iteration()),
            "global_samples": 0, "micro_steps": 0, "skipped_steps": 0,
            "lr_scheduler": None, "fp32_index": fp32_index,
            "opt_indices": {},
            "source_dp_world_size": self.original_dp_degree,
            "source_mp_world_size": self.original_tp_degree,
        }
        with open(os.path.join(out, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        logger.info(f"migrated reference checkpoint {self.dir} → {out} "
                    f"({len(merged)} tensors)")
        return out
