"""Universal checkpoint.

Capability parity with reference ``deepspeed/checkpoint/universal_checkpoint.py``
(:12 ``load_hp_checkpoint_state``, :93) + the offline ``ds_to_universal``
conversion: a checkpoint format loadable at ANY parallelism layout.

The reference needs per-param fp32 *fragment* files with address maps
(utils/tensor_fragment.py:144) because its ZeRO shards are slices of flat
buffers whose layout depends on the (tp, pp, dp) at save time. The TPU
design saves whole logical arrays (GSPMD owns the physical sharding), so
the universal format is simply: one entry per parameter path, fp32 master
weights plus each optimizer-moment tree, with a JSON meta for counters.
Re-sharding on load is a ``device_put`` with the new topology's shardings —
the re-mesh path for elastic restarts (elasticity/) and tp/pp/dp resizes.

Layout::

    <dir>/<tag>_universal/
        meta.json           # step/opt_step/counters, param shapes+dtypes
        fp32.npz            # master weights (param path → fp32 array)
        opt_<name>.npz      # one per optimizer-moment tree (exp_avg, ...)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from ..runtime.checkpoint_engine.checkpoint_engine import (
    checkpoint_meta_path,
    read_latest,
)
from ..utils.logging import log_dist

UNIVERSAL_SUFFIX = "_universal"


def _flatten(tree: Any, prefix: str = "", sep: str = "/") -> Dict[str, Any]:
    flat: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            flat.update(_flatten(v, f"{prefix}{k}{sep}", sep))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            flat.update(_flatten(v, f"{prefix}{i}{sep}", sep))
    else:
        flat[prefix[:-len(sep)] if prefix else prefix] = tree
    return flat


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    nested: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split("/")
        d = nested
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = value
    return nested


def _save_tree_npz(path: str, tree: Any) -> Dict[str, str]:
    """Flatten an array tree into an npz; returns {index_key: param_path}."""
    flat = {k: np.asarray(v, dtype=np.float32)
            for k, v in _flatten(tree).items() if v is not None}
    index = {f"a{i}": k for i, k in enumerate(sorted(flat))}
    np.savez(path, **{f"a{i}": flat[k]
                      for i, k in enumerate(sorted(flat))})
    return index


def _load_tree_npz(path: str, index: Dict[str, str]) -> Dict[str, Any]:
    data = np.load(path, allow_pickle=False)
    return _unflatten({param_path: data[ak] for ak, param_path in index.items()})


def universal_dir(base_dir: str, tag: str) -> str:
    return os.path.join(base_dir, str(tag) + UNIVERSAL_SUFFIX)


def _orbax_to_state_dict(ckpt_dir: str, tag: str,
                         orbax_path: str) -> Dict[str, Any]:
    """Read an orbax-layout checkpoint (the multi-process save path) into
    the pickle-layout state-dict shape. Offloaded optimizer state is
    per-process sidecar files whose host shards this offline converter
    cannot re-assemble — convert those checkpoints from a running engine
    (``save_checkpoint`` on a non-offload engine after load) instead."""
    from ..runtime.checkpoint_engine.orbax_checkpoint_engine import (
        OrbaxCheckpointEngine,
    )

    offload_files = [f for f in os.listdir(os.path.join(ckpt_dir, str(tag)))
                     if f.startswith("offload_pp_rank_")
                     and not f.endswith(".meta")]
    if offload_files:
        raise NotImplementedError(
            f"universal conversion of an offload checkpoint saved by "
            f"multiple processes ({len(offload_files)} per-rank offload "
            f"files in {ckpt_dir}/{tag}) is not supported offline — "
            "resave from an engine with offload disabled, or convert the "
            "single-process pickle layout")
    blob = OrbaxCheckpointEngine(use_async=False).load(orbax_path,
                                                       to_host=True)
    arrays, meta = blob["arrays"], blob.get("meta", {})
    sd: Dict[str, Any] = {
        "module": arrays.get("params"),
        "master": arrays.get("master"),
        "optimizer": arrays.get("opt_state"),
        "offload_optimizer": None,
        "step": arrays.get("step"),
        "opt_step": arrays.get("opt_step", arrays.get("step")),
    }
    for key in ("global_steps", "global_samples", "micro_steps",
                "skipped_steps", "dp_world_size", "mp_world_size",
                "lr_scheduler"):
        sd[key] = meta.get(key)
    return sd


def ds_to_universal(ckpt_dir: str, tag: Optional[str] = None,
                    output_dir: Optional[str] = None) -> str:
    """Convert a saved checkpoint into the universal format — the analog of
    the reference's ``ds_to_universal.py`` offline tool. Returns the
    universal dir path."""
    from ..runtime.checkpoint_engine.checkpoint_engine import (
        ArrayCheckpointEngine,
    )

    if tag is None:
        tag = read_latest(ckpt_dir)
    pickle_path = checkpoint_meta_path(ckpt_dir, tag, "model",
                                       mp_rank=0, dp_rank=0)
    orbax_path = os.path.join(ckpt_dir, str(tag), "orbax_state")
    if os.path.exists(pickle_path + ".meta"):
        engine = ArrayCheckpointEngine()
        sd = engine.load(pickle_path)
    elif os.path.isdir(orbax_path):
        # multi-process saves (engine.save_checkpoint orbax branch) store a
        # sharded array tree + meta sidecar; map it onto the single-file
        # state-dict shape this converter consumes
        sd = _orbax_to_state_dict(ckpt_dir, tag, orbax_path)
    else:
        raise FileNotFoundError(
            f"no checkpoint at {ckpt_dir}/{tag}: neither "
            f"{pickle_path}.meta nor {orbax_path} exists")
    out = universal_dir(output_dir or ckpt_dir, tag)
    os.makedirs(out, exist_ok=True)

    # fp32 master weights; fall back to (upcast) module params when training
    # ran without a separate master copy (pure fp32 runs)
    offload = sd.get("offload_optimizer") or {}
    master = sd.get("master") or offload.get("master")
    source = master if master else sd["module"]
    fp32_index = _save_tree_npz(os.path.join(out, "fp32.npz"), source)

    opt_indices: Dict[str, Dict[str, str]] = {}
    optimizer = sd.get("optimizer")
    if offload:
        # host-offloaded moments live in the offload manager's state dict
        # (keys: master/m/v — see zero/offload.py state_dict). The manager
        # stores moments as raveled 1-D buffers; restore the param shapes so
        # the universal file holds whole logical tensors (loadable by
        # non-offload engines too).
        shapes = {k: np.shape(v) for k, v in _flatten(master or {}).items()}
        for name, key in (("exp_avg", "m"), ("exp_avg_sq", "v")):
            if offload.get(key):
                shaped = {p: (np.asarray(a).reshape(shapes[p])
                              if p in shapes else np.asarray(a))
                          for p, a in _flatten(offload[key]).items()
                          if a is not None}
                opt_indices[name] = _save_tree_npz(
                    os.path.join(out, f"opt_{name}.npz"), shaped)
    elif optimizer:
        # each top-level entry of the optimizer state aligned with params
        # (AdamState: exp_avg / exp_avg_sq; flax serializes namedtuples as
        # {field_name_or_index: tree})
        for key, sub in optimizer.items():
            if sub is None:
                continue
            name = str(key)
            opt_indices[name] = _save_tree_npz(
                os.path.join(out, f"opt_{name}.npz"), sub)

    def as_int(v, default=0):
        return int(np.asarray(v)) if v is not None else default

    def jsonify(v):
        if isinstance(v, dict):
            return {k: jsonify(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [jsonify(x) for x in v]
        if isinstance(v, np.ndarray):
            return v.item() if v.ndim == 0 else v.tolist()
        if isinstance(v, (np.integer, np.floating)):
            return v.item()
        return v

    meta = {
        "tag": str(tag),
        "step": as_int(sd.get("step")),
        "opt_step": as_int(sd.get("opt_step", sd.get("step"))),
        "global_steps": as_int(sd.get("global_steps")),
        "global_samples": as_int(sd.get("global_samples")),
        "micro_steps": as_int(sd.get("micro_steps")),
        "skipped_steps": as_int(sd.get("skipped_steps")),
        "lr_scheduler": jsonify(sd.get("lr_scheduler")),
        "fp32_index": fp32_index,
        "opt_indices": opt_indices,
        "source_dp_world_size": as_int(sd.get("dp_world_size"), 1),
        "source_mp_world_size": as_int(sd.get("mp_world_size"), 1),
    }
    with open(os.path.join(out, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    log_dist(f"wrote universal checkpoint {out}", ranks=[0])
    return out


def load_universal(univ_dir: str) -> Dict[str, Any]:
    """Read a universal checkpoint dir → {meta, fp32, opt:{name: tree}}."""
    with open(os.path.join(univ_dir, "meta.json")) as f:
        meta = json.load(f)
    fp32 = _load_tree_npz(os.path.join(univ_dir, "fp32.npz"),
                          meta["fp32_index"])
    opt = {name: _load_tree_npz(os.path.join(univ_dir, f"opt_{name}.npz"), idx)
           for name, idx in meta.get("opt_indices", {}).items()}
    return {"meta": meta, "fp32": fp32, "opt": opt}
