"""Fused paged-attention decode as a Pallas TPU kernel.

The vLLM PagedAttention insight, aimed at this repo's hottest serving op:
the kernel reads the :class:`~deepspeed_tpu.serving.paged_pool.PagedKVPool`
page table IN PLACE instead of gathering pages into a dense per-slot view
first. The dense round-trip (``KVCacheSpec.dense_from_pages`` gather →
dense attention → ``_scatter_cols`` writeback) materializes O(slots ×
max_seq_len) K/V every step; here the page table rides scalar prefetch
(SMEM) and the K/V BlockSpec index maps resolve ``table[slot, j]`` per
grid step, so HBM traffic is one DMA per LIVE page — the pool's physical
pages are the only cache bytes ever read.

Parity contract (the "dense oracle" discipline): the per-step compute is
op-for-op the dense decode kernel's
(:func:`~deepspeed_tpu.ops.attention.decode_attention._decode_kernel` —
same online-softmax update order, same masking, same scratch shapes) with
the position block pinned to ONE PAGE. A single-token call is therefore
bitwise-identical to ``decode_attention(q, dense_k, dense_v, lengths,
block_s=page_size)`` on the gathered dense view — in interpret mode on
CPU and natively on TPU — which is what lets the serving tests pin the
paged-kernel arm against the dense path exactly (TransformerConfig's
``decode_block`` pins the oracle's block granule to the page size).

Garbage is masked by length, never by table lookups: dead grid steps
(pages past a slot's live length) clamp their index map to the slot's
LAST LIVE page — consecutive identical block indices elide the DMA
(Pallas revisiting rule), so bandwidth tracks the live length — and
sentinel table entries (``num_pages`` = unmapped) clip to a real page
exactly like the dense gather's ``mode="clip"``; both reads are masked
to ``NEG_INF`` before the softmax, so their values never reach the
output. Supports 1..SUBLANES query rows per slot (plain decode T=1;
speculative verify T=K+1) with per-row causal masking, GQA, ALiBi, and
the int8/int32-packed quantized cache tiers (scales paged alongside,
folded into the score/probability rows like the dense kernel).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, SUBLANES, _interpret

__all__ = ["paged_decode_attention", "MAX_QUERY_ROWS"]

# one kernel serves decode (T=1) and speculative verify (T=K+1): query
# rows live on the SUBLANES axis of the score tile, so the row budget is
# the sublane count — pools fall back to the dense composition beyond it
MAX_QUERY_ROWS = SUBLANES


def _paged_kernel(start_ref, slope_ref, table_ref, q_ref, k_ref, v_ref,
                  o_ref, acc_ref, m_ref, l_ref, *, scale: float,
                  page_size: int, num_rows: int, alibi: bool,
                  compute_dtype=None, k_scale_ref=None, v_scale_ref=None,
                  packed: bool = False):
    # start_ref/slope_ref/table_ref are scalar-prefetch SMEM arrays:
    # (B,), (H,) and (B, pages_per_slot). The compute below mirrors
    # decode_attention._decode_kernel line for line (the bitwise-parity
    # contract in the module docstring); the ONLY differences are where
    # K/V blocks come from (page-indexed index maps, not contiguous
    # offsets) and that query rows 0..num_rows-1 carry their own causal
    # limit (row t sees cache positions <= start + t).
    j = pl.program_id(2)
    num_p = pl.num_programs(2)
    start = start_ref[pl.program_id(0)]
    slope = slope_ref[pl.program_id(1)]
    block_start = j * page_size

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(block_start < start + num_rows)
    def _compute():
        q = q_ref[0]                                      # (SUBLANES, D)
        k = k_ref[0, 0]                                   # (Dc, page_size)
        v = v_ref[0, 0]
        if k_scale_ref is not None:
            if packed:
                k = pltpu.bitcast(k, jnp.int8).astype(compute_dtype)
                v = pltpu.bitcast(v, jnp.int8).astype(compute_dtype)
            else:
                k = k.astype(compute_dtype)
                v = v.astype(compute_dtype)
        s = jax.lax.dot_general(q, k, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if k_scale_ref is not None:
            s = s * k_scale_ref[0, 0]                     # (1, page) scale
        pos = block_start + jax.lax.broadcasted_iota(
            jnp.int32, (SUBLANES, page_size), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (SUBLANES, page_size), 0)
        if alibi:
            # row t's query sits at absolute position start + t
            s = s + slope * (pos - (start + row)).astype(jnp.float32)
        s = jnp.where(pos <= start + row, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=1, keepdims=True), l_ref.shape)
        if v_scale_ref is not None:
            p = p * v_scale_ref[0, 0]                     # (1, page) scale
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == num_p - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, table: jax.Array,
                           starts: jax.Array, *,
                           scale: Optional[float] = None,
                           alibi_slopes: Optional[jax.Array] = None,
                           k_scale_pages: Optional[jax.Array] = None,
                           v_scale_pages: Optional[jax.Array] = None
                           ) -> jax.Array:
    """Cached attention over paged K/V: softmax(q·K^T + bias) · V with
    K/V resolved through a per-slot page table inside the kernel.

    Args:
      q: (B, T, H, D) current-step queries, 1 <= T <= MAX_QUERY_ROWS.
        Row ``t`` of slot ``b`` attends cache positions
        ``[0, starts[b] + t]`` (its own column included — the caller has
        already written this step's T columns into the pages).
      k_pages/v_pages: (P, KV, Dc, page_size) ONE layer's physical page
        pool, H % KV == 0 (GQA). May be int8, or int32-packed
        (Dc = D // 4) when scales are given.
      table: (B, pages_per_slot) int32 page table; ``P`` is the
        unmapped sentinel (clipped to a real page, masked by length —
        the dense gather's ``mode="clip"`` discipline).
      starts: (B,) int32 cache length BEFORE this step's tokens (the
        slot pool's ``index`` mirror at dispatch).
      alibi_slopes: optional (H,) ALiBi slopes.
      k_scale_pages/v_scale_pages: (P, KV, page_size) fp32 per-column
        dequantization scales for a quantized page pool.
    Returns (B, T, H, D) in q's dtype.
    """
    B, T, H, D = q.shape
    P, KV, Dc, ps = k_pages.shape
    maxP = table.shape[1]
    assert H % KV == 0, f"H={H} not a multiple of KV={KV}"
    assert 1 <= T <= MAX_QUERY_ROWS, \
        f"paged kernel handles 1..{MAX_QUERY_ROWS} query rows, got {T}"
    assert (k_scale_pages is None) == (v_scale_pages is None), \
        "provide both k_scale_pages and v_scale_pages or neither"
    quantized = k_scale_pages is not None
    packed = quantized and k_pages.dtype == jnp.int32
    assert Dc == (D // 4 if packed else D), \
        f"page head dim {Dc} vs query head dim {D} (packed={packed})"
    rep = H // KV
    out_dtype = q.dtype
    # dtype harmonization — identical to decode_attention's wrapper so
    # the two kernels' MXU operands (and thus outputs) match bitwise
    if quantized:
        compute_dtype = q.dtype if q.dtype == jnp.bfloat16 else jnp.float32
        q = q.astype(compute_dtype)
    else:
        compute_dtype = k_pages.dtype
        q = q.astype(k_pages.dtype)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    starts = jnp.broadcast_to(jnp.asarray(starts, jnp.int32), (B,))
    if alibi_slopes is None:
        slopes = jnp.zeros((H,), jnp.float32)
        alibi = False
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        alibi = True
    table = jnp.asarray(table, jnp.int32)

    # query rows ride the SUBLANES axis: pad T up to the full sublane
    # tile (dead rows compute with a wider causal window and are sliced
    # off — never all-masked, so no NaN risk) and fold heads into the
    # leading grid axis like the dense kernel's q3
    q4 = q.transpose(0, 2, 1, 3)                          # (B, H, T, D)
    if T < SUBLANES:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, SUBLANES - T), (0, 0)))
    q3 = q4.reshape(B * H, SUBLANES, D)

    grid = (B, H, maxP)

    def kv_index(b, h, j, start_ref, slope_ref, table_ref):
        # clamp dead steps to the slot's last LIVE page (consecutive
        # identical indices elide the DMA — bandwidth tracks the live
        # length), then clip sentinel entries into range (masked reads)
        last_live = jnp.maximum(
            (start_ref[b] + T + ps - 1) // ps - 1, 0)
        pid = table_ref[b, jnp.minimum(j, last_live)]
        return (jnp.minimum(pid, P - 1), h // rep, 0, 0)

    in_specs = [
        pl.BlockSpec((1, SUBLANES, D), lambda b, h, j, *_: (b * H + h, 0, 0)),
        pl.BlockSpec((1, 1, Dc, ps), kv_index),
        pl.BlockSpec((1, 1, Dc, ps), kv_index),
    ]
    operands = [starts, slopes, table, q3, k_pages, v_pages]
    if quantized:
        # scales ride as (P, KV, 1, page_size) so the (1, 1, 1, ps)
        # block lands on LANES, matching s/p (same trick as the dense
        # kernel's (B, KV, 1, S) reshape)
        in_specs += [pl.BlockSpec((1, 1, 1, ps), kv_index),
                     pl.BlockSpec((1, 1, 1, ps), kv_index)]
        operands += [
            k_scale_pages.astype(jnp.float32).reshape(P, KV, 1, ps),
            v_scale_pages.astype(jnp.float32).reshape(P, KV, 1, ps)]

        def kernel(start_ref, slope_ref, table_ref, q_ref, k_ref, v_ref,
                   ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref):
            _paged_kernel(start_ref, slope_ref, table_ref, q_ref, k_ref,
                          v_ref, o_ref, acc_ref, m_ref, l_ref, scale=scale,
                          page_size=ps, num_rows=T, alibi=alibi,
                          compute_dtype=compute_dtype,
                          k_scale_ref=ks_ref, v_scale_ref=vs_ref,
                          packed=packed)
    else:
        kernel = functools.partial(_paged_kernel, scale=scale, page_size=ps,
                                   num_rows=T, alibi=alibi)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, SUBLANES, D),
                               lambda b, h, j, *_: (b * H + h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((SUBLANES, D), jnp.float32),
            pltpu.VMEM((SUBLANES, LANES), jnp.float32),
            pltpu.VMEM((SUBLANES, LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, SUBLANES, D), q.dtype),
        interpret=_interpret(),
    )(*operands)
    out = out.reshape(B, H, SUBLANES, D)[:, :, :T]
    return out.transpose(0, 2, 1, 3).astype(out_dtype)
