"""Fused KV-cache decode attention as a Pallas TPU kernel.

TPU-native equivalent of the reference's generation hot path — the
``softmax_context`` fused attention-with-KV-cache kernel
(csrc/transformer/inference/csrc/pt_binding.cpp:1910-1975): one query
token per sequence attends over the cache with length masking, softmax and
the value reduction fused in a single pass. Decode is HBM-bandwidth bound
(the whole cache is read every step); fusing keeps the (H, S) score matrix
in VMEM instead of HBM and reads K/V exactly once.

Layout: q (B, H, D); k/v cache (B, KV, D, S) — the model's cache layout:
D on SUBLANES, positions on LANES. Positions-minor is deliberate: S is
always a multiple of 128, so no tile is ever lane-padded (a (S, D=64)
cache pads every 128-lane tile 2x — measured as the capacity killer in
the round-5 ladder), and the int8-packed int32 container keeps whole
positions per word so cache writes stay word-aligned plain
dynamic-update-slices. The kernel's two dots contract directly against
this orientation (q·K over D-sublanes, p·V over position-lanes) — no
transpose anywhere. Grouped-query attention maps query head h to
kv head h // (H // KV) in the BlockSpec index map. ``lengths`` (B,) masks
cache slots >= length. Optional ALiBi slopes add the reference's alibi
bias. Blocks past a sequence's length are dead: ``pl.when`` skips their
compute, and the K/V index maps CLAMP dead grid steps to the sequence's
last live block — consecutive grid steps with the same block index elide
the DMA (Pallas revisiting rule), so HBM traffic ALSO tracks the live
length (one redundant block fetch at the boundary), not the allocated
capacity. Decoding at position p costs O(p), the realistic generate()
regime where p << max_seq_len.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, SUBLANES, _interpret

DEFAULT_BLOCK_S = 1024
LONG_CACHE_BLOCK_S = 4096  # >= 8k caches: grid overhead, not bandwidth,
# bounds the 1024 block — the kv_int8_bench block sweep measures 4096
# fastest for both bf16 and int8 at 16k (BASELINE.md round-5 KV section);
# short live lengths only pay one partially-dead block (the index-map
# clamp elides the rest), a sub-ms cost


def preferred_block_for(live_len: int) -> int:
    """Preferred decode block for an EXPECTED LIVE length (prompt +
    budget), as opposed to the allocated capacity. NOTE the measured
    e2e A/B came out NEGATIVE for auto-deriving the block from the
    budget (every arm 5-15% slower at live 1536/4352 in an 8k cache):
    the index-map clamp already elides dead-block DMA, so decode at
    these shapes is grid-overhead bound and fewer, larger grid steps
    win even when the last live block is mostly dead (BASELINE.md
    round-5 KV e2e section). engine.generate therefore keeps the
    allocation-based block; this helper + the ``block_hint`` plumbing
    remain for callers with measured wins at their own shapes."""
    return LONG_CACHE_BLOCK_S if live_len >= 8192 else DEFAULT_BLOCK_S


def pick_block_s(cache_len: int, preferred: Optional[int] = None) -> int:
    """Largest power-of-two block <= preferred that divides the cache
    length (the kernel requires S % block_s == 0). Returns the largest
    power-of-two divisor when that's below ``preferred``. Default
    preference is length-aware: 1024 below 8k, 4096 from 8k up."""
    if preferred is None:
        preferred = LONG_CACHE_BLOCK_S if cache_len >= 8192 \
            else DEFAULT_BLOCK_S
    block = preferred
    while block > 1 and cache_len % block != 0:
        block //= 2
    return block


def quantize_kv_rows(x: jax.Array):
    """Per-row symmetric int8 quantization over the last axis: returns
    (int8 values, fp32 scales) with ``x ≈ int8 * scale[..., None]``.
    The KV-cache quantizer: one scale per (batch, kv-head, position)."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def pack_int8_sublanes(x8: jax.Array) -> jax.Array:
    """Pack int8 (..., R, C) into an int32 container (..., R//4, C):
    byte ``j`` of word ``(i, c)`` is element ``(4*i + j, c)``.

    Why: Mosaic stores int8 arrays in a (4, 1)-packed tiled layout; when
    an int8 KV cache rides a ``lax.scan``/while-loop carry, a
    layout-conversion copy defeats XLA's in-place buffer aliasing and the
    decode program double-buffers the cache (measured: BASELINE.md
    round-5 "capacity ladder" section — the 485 MB-over OOM at int8 B=4).
    int32 carries use the native (8, 128) tiling and alias in place, so
    the same bytes in an int32 container restore O(cache) memory.

    For the (B, KV, D, S) cache this packs along D (the sublane dim), so
    each word holds 4 head-dim rows of one position and cache writes stay
    word-aligned. The byte order equals the TPU's own sublane packing, so
    inside the kernel ``pltpu.bitcast(words, int8)`` reinterprets the
    (D//4, block) int32 tile as the (D, block) int8 tile FOR FREE — no
    shifts, no relayout (verified identical on real v5e and in interpret
    mode)."""
    R = x8.shape[-2]
    assert R % 4 == 0, f"packed dim {R} not a multiple of 4"
    w = (x8.reshape(*x8.shape[:-2], R // 4, 4, x8.shape[-1])
         .astype(jnp.int32) & jnp.int32(0xFF))
    return (w[..., 0, :] | (w[..., 1, :] << 8) | (w[..., 2, :] << 16)
            | (w[..., 3, :] << 24))


def unpack_int8_sublanes(w: jax.Array, dtype=jnp.int8) -> jax.Array:
    """Inverse of :func:`pack_int8_sublanes` in plain jnp (for the einsum
    fallback and host-side round trips): (..., R//4, C) -> (..., R, C).
    Arithmetic right shift sign-extends each byte."""
    parts = jnp.stack(
        [((w << (24 - 8 * j)) >> 24) for j in range(4)], axis=-2)
    return parts.reshape(*w.shape[:-2], w.shape[-2] * 4,
                         w.shape[-1]).astype(dtype)


def _decode_kernel(len_ref, slope_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale: float, block_s: int,
                   alibi: bool, compute_dtype=None,
                   k_scale_ref=None, v_scale_ref=None, packed: bool = False):
    # len_ref/slope_ref are scalar-prefetch SMEM arrays: (B,) and (H,).
    # With an int8-quantized cache, k_scale_ref/v_scale_ref carry the
    # per-row (per token, per kv-head) dequantization scales and are
    # threaded in as extra INPUT refs (before o_ref at call time; bound
    # here by keyword from the wrapper's arg shuffle).
    j = pl.program_id(2)
    num_s = pl.num_programs(2)
    length = len_ref[pl.program_id(0)]
    slope = slope_ref[pl.program_id(1)]
    block_start = j * block_s

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(block_start < length)
    def _compute():
        # MXU operands stay in the compute dtype (bf16 at full rate on
        # v5e); fp32 stats/accumulator; scale applied to fp32 s.
        # int8 path: int8 values <= 127 are EXACT in bf16, so the cache
        # casts losslessly and the dequant scales fold into the score row
        # (k) and the probability row (v) — two (SUBLANES, block_s) VPU
        # multiplies instead of dequantizing the (block_s, D) blocks.
        q = q_ref[0]                                      # (1, D)
        qb = jnp.broadcast_to(q, (SUBLANES, q.shape[-1]))
        k = k_ref[0, 0]                                   # (D, block_s)
        v = v_ref[0, 0]
        if k_scale_ref is not None:
            if packed:
                # int32-packed int8 cache: the (D//4, block) int32 tile
                # IS the (D, block) int8 tile bit-for-bit (sublane byte
                # order) — bitcast reinterprets it for free. int8
                # magnitudes are exact in bf16, so the cast is lossless.
                k = pltpu.bitcast(k, jnp.int8).astype(compute_dtype)
                v = pltpu.bitcast(v, jnp.int8).astype(compute_dtype)
            else:
                k = k.astype(compute_dtype)
                v = v.astype(compute_dtype)
        s = jax.lax.dot_general(qb, k, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if k_scale_ref is not None:
            s = s * k_scale_ref[0, 0]                     # (1, block_s) scale
        pos = block_start + jax.lax.broadcasted_iota(
            jnp.int32, (SUBLANES, block_s), 1)
        if alibi:
            # reference alibi bias: slope * (key_pos - query_pos); the
            # decoding query sits at position length - 1
            s = s + slope * (pos - (length - 1)).astype(jnp.float32)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=1, keepdims=True), l_ref.shape)
        if v_scale_ref is not None:
            p = p * v_scale_ref[0, 0]                     # (1, block_s) scale
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == num_s - 1)
    def _finish():
        l = jnp.maximum(l_ref[:1, :1], 1e-30)
        o_ref[0] = (acc_ref[:1] / l).astype(o_ref.dtype)


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     lengths: jax.Array, *, scale: Optional[float] = None,
                     alibi_slopes: Optional[jax.Array] = None,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None,
                     block_s: int = DEFAULT_BLOCK_S) -> jax.Array:
    """Single-token cached attention: softmax(q·K^T + bias) · V.

    Args:
      q: (B, H, D) current-step queries.
      k_cache/v_cache: (B, KV, D, S) with H % KV == 0 (GQA) — positions
        minor (see module docstring: no lane padding, aligned writes).
        May be int8 (quantized KV cache) when ``k_scale``/``v_scale``
        are given, or int32 (B, KV, D//4, S) — the
        :func:`pack_int8_sublanes` container whose carries alias in
        place through ``lax.scan`` (the in-kernel unpack is a free
        ``pltpu.bitcast``).
      lengths: (B,) or scalar int32 — valid cache slots per sequence
        (INCLUDING the current token, already written to the cache).
      alibi_slopes: optional (H,) ALiBi slopes.
      k_scale/v_scale: (B, KV, S) fp32 per-row dequantization scales for
        an int8 cache (row value = int8 * scale). Halves the cache's HBM
        traffic — the resource decode is bound by; the scales fold into
        the score/probability rows, so no dequantized (block_s, D) block
        is ever materialized.
    Returns (B, H, D) in q's dtype.
    """
    B, H, D = q.shape
    _, KV, Dc, S = k_cache.shape
    assert H % KV == 0, f"H={H} not a multiple of KV={KV}"
    assert (k_scale is None) == (v_scale is None), \
        "provide both k_scale and v_scale or neither"
    quantized = k_scale is not None
    packed = quantized and k_cache.dtype == jnp.int32
    assert Dc == (D // 4 if packed else D), \
        f"cache head dim {Dc} vs query head dim {D} (packed={packed})"
    rep = H // KV
    # MXU operands must share a dtype (the kernel no longer upcasts to
    # fp32 — bf16 runs at full MXU rate); harmonize q to the cache dtype
    # (for int8 caches the compute dtype is q's own) and restore the
    # caller's dtype on the way out
    out_dtype = q.dtype
    if quantized:
        compute_dtype = q.dtype if q.dtype == jnp.bfloat16 else jnp.float32
        q = q.astype(compute_dtype)
    else:
        compute_dtype = k_cache.dtype
        q = q.astype(k_cache.dtype)
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (B,))
    if alibi_slopes is None:
        slopes = jnp.zeros((H,), jnp.float32)
        alibi = False
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        alibi = True
    block_s = min(block_s, S)
    assert S % block_s == 0, f"cache length {S} % block_s {block_s} != 0"

    grid = (B, H, S // block_s)
    # q/out carry a dummy middle dim so every block's trailing two dims
    # equal the array dims (the Mosaic tiling contract); lengths/slopes ride
    # scalar prefetch (SMEM, fully resident) and index maps receive them as
    # trailing args per the PrefetchScalarGridSpec contract
    q3 = q.reshape(B * H, 1, D)

    def kv_index(b, h, j, len_ref, slope_ref):
        # clamp dead steps to the last LIVE block: consecutive identical
        # indices elide the DMA, so bandwidth tracks the live length
        last_live = jnp.maximum(
            (len_ref[b] + block_s - 1) // block_s - 1, 0)
        return (b, h // rep, 0, jnp.minimum(j, last_live))

    scale_index = kv_index

    in_specs = [
        pl.BlockSpec((1, 1, D), lambda b, h, j, *_: (b * H + h, 0, 0)),
        pl.BlockSpec((1, 1, Dc, block_s), kv_index),
        pl.BlockSpec((1, 1, Dc, block_s), kv_index),
    ]
    operands = [lengths, slopes, q3, k_cache, v_cache]
    if quantized:
        # scales ride as (B, KV, 1, S): the block (1, 1, 1, block_s) puts
        # them on LANES, matching s/p's lane layout (and Mosaic's tiling
        # contract — a (1, block_s) trailing block would not tile)
        in_specs += [pl.BlockSpec((1, 1, 1, block_s), scale_index),
                     pl.BlockSpec((1, 1, 1, block_s), scale_index)]
        operands += [k_scale.astype(jnp.float32).reshape(B, KV, 1, S),
                     v_scale.astype(jnp.float32).reshape(B, KV, 1, S)]

        def kernel(len_ref, slope_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                   o_ref, acc_ref, m_ref, l_ref):
            _decode_kernel(len_ref, slope_ref, q_ref, k_ref, v_ref, o_ref,
                           acc_ref, m_ref, l_ref, scale=scale,
                           block_s=block_s, alibi=alibi,
                           compute_dtype=compute_dtype,
                           k_scale_ref=ks_ref, v_scale_ref=vs_ref,
                           packed=packed)
    else:
        kernel = functools.partial(_decode_kernel, scale=scale,
                                   block_s=block_s, alibi=alibi)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, D),
                               lambda b, h, j, *_: (b * H + h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((SUBLANES, D), jnp.float32),
            pltpu.VMEM((SUBLANES, LANES), jnp.float32),
            pltpu.VMEM((SUBLANES, LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, 1, D), q.dtype),
        interpret=_interpret(),
    )(*operands)
    return out.reshape(B, H, D).astype(out_dtype)
