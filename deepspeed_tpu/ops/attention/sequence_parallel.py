"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference (v0.9.5) predates DeepSpeed-Ulysses and has NO sequence
parallelism (SURVEY §5.7, grep-verified); its long-sequence levers are sparse
attention and activation partitioning. This module is the TPU-idiomatic
long-context answer the build plan calls for (SURVEY §7 step 12): a
first-class ``seq`` mesh axis with two interchangeable attention strategies,

* **ring attention** — K/V chunks rotate around the ``seq`` axis via
  ``lax.ppermute`` while each device keeps its Q chunk; per-step partial
  attention folds into a running (max, sum, acc) online softmax, so the full
  (S×S) score matrix never materializes and peak memory is O(S/sp) per
  device. The ppermute rides neighbor ICI links — bandwidth-optimal on a
  torus. (Liu et al., Ring Attention with Blockwise Transformers, 2023.)
* **Ulysses all-to-all** — two ``lax.all_to_all``s re-shard the activations
  from sequence-sharded to head-sharded, run *local* full attention (dense or
  the Pallas flash kernel), and scatter back. Comm volume is O(S·C/sp) per
  device (vs allgathering K/V = O(S·C)), the DeepSpeed-Ulysses insight.

Both are exposed (a) as ``shard_map``-wrapped drop-ins taking globally-shaped
arrays, and (b) as ``*_local`` collectives usable inside an existing
``shard_map``/pjit region. ``DistributedAttention`` mirrors the module API
DeepSpeed later shipped (deepspeed.sequence.layer.DistributedAttention) so
users migrating from newer DeepSpeed find the same surface.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, get_mesh

NEG_INF = -1e30


def _dense_attention(q, k, v, *, causal: bool, scale: float,
                     q_offset=0, k_offset=0):
    """Plain blockwise-dense attention in fp32 with absolute-position causal
    masking (offsets give each shard its global coordinates)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
        cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# ring attention (collective form — call inside shard_map over `axis_name`)
# ---------------------------------------------------------------------------
def ring_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS,
                         causal: bool = True,
                         scale: Optional[float] = None):
    """Ring attention over ``axis_name``; q/k/v are the LOCAL sequence shards
    shaped (B, S_local, H, D). Returns the local shard of the output.

    Step s: every device holds K/V chunk ``(my_index - s) mod sp`` and folds
    its partial attention into the online-softmax state, then passes the
    chunk to its right neighbor. Fully-causally-masked steps still occupy a
    ring slot (the rotation must complete) but their contribution is exactly
    zero via the mask term.
    """
    B, S_local, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32) * scale
    q_pos = my * S_local + jax.lax.broadcasted_iota(jnp.int32, (S_local, S_local), 0)

    def step(carry, s):
        acc, m, l, k_cur, v_cur = carry
        chunk = jax.lax.rem(my - s + sp, sp)
        scores = jnp.einsum("bthd,bshd->bhts", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = chunk * S_local + jax.lax.broadcasted_iota(
                jnp.int32, (S_local, S_local), 1)
            mask = (q_pos >= k_pos)[None, None]
            scores = jnp.where(mask, scores, NEG_INF)
            maskf = mask.astype(jnp.float32)
        else:
            maskf = None
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        if maskf is not None:
            p = p * maskf  # kills spurious exp(0)=1 on fully-masked rows
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhts,bshd->bhtd", p, v_cur.astype(jnp.float32))
        k_nxt, v_nxt = jax.lax.ppermute(
            (k_cur, v_cur), axis_name,
            [(i, (i + 1) % sp) for i in range(sp)])
        return (acc_new, m_new, l_new, k_nxt, v_nxt), None

    acc0 = jnp.zeros((B, H, S_local, D), jnp.float32)
    m0 = jnp.full((B, H, S_local, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S_local, 1), jnp.float32)
    # mark the fresh carries as device-varying over the same manual axes as q
    # (new-style shard_map type-checks varying-axis sets through scan)
    vma = tuple(getattr(jax.typeof(q), "vma", ()) or ())
    if vma:
        acc0, m0, l0 = (jax.lax.pcast(x, vma, to="varying")
                        for x in (acc0, m0, l0))
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(sp))
    out = acc / jnp.maximum(l, 1e-20)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses (collective form)
# ---------------------------------------------------------------------------
def ulysses_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS,
                            causal: bool = True,
                            scale: Optional[float] = None,
                            attn_fn: Optional[Callable] = None):
    """DeepSpeed-Ulysses-style attention over ``axis_name``.

    q/k/v: local shards (B, S_local, H, D) with H divisible by the axis size.
    all_to_all #1 scatters heads / gathers sequence → (B, S, H/sp, D); local
    full attention (``attn_fn`` or dense, e.g. the Pallas flash kernel);
    all_to_all #2 scatters sequence / gathers heads back.
    """
    D = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    sp = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    if H % sp != 0:
        raise ValueError(
            f"Ulysses requires the local head count ({H}) to be divisible by "
            f"the '{axis_name}' axis size ({sp}); use ring attention for "
            f"head counts that don't divide")
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # seq-sharded → head-sharded
    qh = a2a(q, split_axis=2, concat_axis=1)
    kh = a2a(k, split_axis=2, concat_axis=1)
    vh = a2a(v, split_axis=2, concat_axis=1)
    if attn_fn is None:
        o = _dense_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        o = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    # head-sharded → seq-sharded
    return a2a(o, split_axis=1, concat_axis=2)


# ---------------------------------------------------------------------------
# shard_map wrappers taking GLOBAL arrays
# ---------------------------------------------------------------------------
def _shard_map():
    try:
        from jax import shard_map  # jax >= 0.8
        return shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
        return shard_map


def _seq_specs(batch_axes, axis_name, head_axes):
    return P(batch_axes, axis_name, head_axes, None)


def ring_attention(q, k, v, *, mesh=None, axis_name: str = SEQ_AXIS,
                   causal: bool = True, scale: Optional[float] = None,
                   batch_axes=(DATA_AXIS, EXPERT_AXIS), head_axes=None):
    """Global-view ring attention: (B, S, H, D) arrays, batch sharded over
    ``batch_axes``, sequence sharded over ``axis_name``; ``head_axes`` lets
    tensor parallelism shard the head dim (composes: ring per head shard)."""
    mesh = mesh or get_mesh()
    spec = _seq_specs(batch_axes, axis_name, head_axes)
    fn = functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale)
    return _shard_map()(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)(q, k, v)


def ulysses_attention(q, k, v, *, mesh=None, axis_name: str = SEQ_AXIS,
                      causal: bool = True, scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      batch_axes=(DATA_AXIS, EXPERT_AXIS), head_axes=None):
    """Global-view Ulysses attention (see :func:`ulysses_attention_local`)."""
    mesh = mesh or get_mesh()
    spec = _seq_specs(batch_axes, axis_name, head_axes)
    fn = functools.partial(ulysses_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale, attn_fn=attn_fn)
    return _shard_map()(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)(q, k, v)


class DistributedAttention:
    """Sequence-parallel attention wrapper, API-compatible with the module
    DeepSpeed later shipped as ``deepspeed.sequence.layer.DistributedAttention``
    (post-0.10.2): wraps a *local* attention callable and handles the
    sequence↔head resharding around it.

    ``local_attn(q, k, v, *, causal, scale) -> out`` operates on
    head-sharded, full-sequence tensors (B, S, H_local, D). Only the
    "ulysses" strategy uses it; ring computes its own blockwise softmax, so
    combining ring with ``local_attn`` is rejected.
    """

    def __init__(self, local_attn: Optional[Callable] = None,
                 *, mesh=None, axis_name: str = SEQ_AXIS,
                 strategy: str = "ulysses", causal: bool = True,
                 scale: Optional[float] = None,
                 batch_axes=(DATA_AXIS, EXPERT_AXIS), head_axes=None):
        assert strategy in ("ulysses", "ring"), strategy
        if strategy == "ring" and local_attn is not None:
            raise ValueError(
                "strategy='ring' cannot use local_attn (ring attention "
                "computes blockwise softmax internally); use 'ulysses'")
        self.local_attn = local_attn
        self.mesh = mesh
        self.axis_name = axis_name
        self.strategy = strategy
        self.causal = causal
        self.scale = scale
        self.batch_axes = batch_axes
        self.head_axes = head_axes

    def __call__(self, q, k, v):
        if self.strategy == "ring":
            return ring_attention(q, k, v, mesh=self.mesh,
                                  axis_name=self.axis_name, causal=self.causal,
                                  scale=self.scale, batch_axes=self.batch_axes,
                                  head_axes=self.head_axes)
        return ulysses_attention(q, k, v, mesh=self.mesh,
                                 axis_name=self.axis_name, causal=self.causal,
                                 scale=self.scale, attn_fn=self.local_attn,
                                 batch_axes=self.batch_axes,
                                 head_axes=self.head_axes)
