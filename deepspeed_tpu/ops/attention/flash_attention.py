"""Fused flash attention (forward + backward) as Pallas TPU kernels.

TPU-native replacement for the reference's fused attention kernels — the
training transformer kernel's softmax/attention path
(csrc/transformer/softmax_kernels.cu + ds_transformer_cuda.cpp) and the
flash-style parity piece called out in SURVEY §2.2. Online-softmax tiling
(Flash-Attention-2 style) keeps the (T×T) score matrix out of HBM: scores are
computed block-by-block in VMEM, the MXU does the two matmuls per block, and
running max/sum statistics rescale the accumulator.

VMEM stays O(block), not O(seq): the KV axis is a grid dimension (TPU grids
execute sequentially, innermost-last, so VMEM scratch carries the
accumulator/stats across KV iterations of one Q block) — Pallas DMAs only the
current (block, d) tiles. Causal masking skips fully-masked blocks.

Layout: (batch, seq, heads, head_dim) in, same out. Backward follows the
standard recompute scheme: store only ``lse`` (per-row log-sum-exp); dq and
dk/dv are two kernels gridding the opposite axes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# checkpoint_name tags on attention-kernel outputs (see _flash_attention_fwd);
# remat policies compose save_only_these_names(*ATTN_SAVE_NAMES) so the
# backward pass reuses the forward kernel's (out, lse) instead of re-running it
ATTN_SAVE_NAMES = ("flash_out", "flash_lse")
# TPU vector layout: fp32 tiles are (8 sublanes, 128 lanes). Row statistics
# (lse, delta) are carried replicated across a size-8 sublane dim so their
# blocks satisfy the (8, 128) tiling rule; stats scratch is lane-width.
SUBLANES = 8
LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward: grid (bh, q_blocks, kv_blocks), scratch carries (acc, m, l)
# ---------------------------------------------------------------------------
def _fwd_single_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                       scale: float, causal: bool):
    """One-KV-block specialization (block_k == seq_k): plain block softmax.

    The tuned table picks block_k = seq for seq <= 1024 (and 512x1024 tiles
    generally), where the KV grid axis has a single step — the online-softmax
    running stats (acc rescale, m/l scratch round-trips, alpha exps) are pure
    overhead there. This kernel computes max/exp/sum once and writes out
    directly from registers/VMEM."""
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
    acc = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_row = (m + jnp.log(l))[:, 0]
    lse_ref[0] = jnp.broadcast_to(lse_row[None, :], lse_ref.shape[1:])


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale: float, causal: bool):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)
    num_kv = pl.num_programs(2)
    q_start = qi * block_q
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks entirely above the diagonal
    live = (not causal) or (k_start < q_start + block_q)

    @pl.when(jnp.asarray(live))
    def _compute():
        # MXU operands stay in the input dtype (bf16 in training): v5e runs
        # bf16xbf16->fp32 at full rate but fp32 matmuls at a fraction of it.
        # Accumulation/statistics are fp32 (preferred_element_type); p is
        # cast back to the input dtype for the PV dot (FA2 discipline).
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_row = (m_ref[:, :1] + jnp.log(l))[:, 0]  # (block_q,)
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], lse_ref.shape[1:])


def _flash_fwd(q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int):
    bh, seq_q, d = q.shape
    _, seq_k, _ = k.shape
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    assert seq_q % block_q == 0 and seq_k % block_k == 0, \
        f"seq ({seq_q},{seq_k}) must be divisible by blocks ({block_q},{block_k})"

    if seq_k == block_k:
        # single KV step: no online stats needed (see _fwd_single_kernel)
        out, lse = pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale=scale, causal=causal),
            grid=(bh, seq_q // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), lambda b, i: (b, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), lambda b, i: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, SUBLANES, block_q), lambda b, i: (b, 0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
                jax.ShapeDtypeStruct((bh, SUBLANES, seq_q), jnp.float32),
            ],
            interpret=_interpret(),
        )(q, k, v)
        return out, lse

    grid = (bh, seq_q // block_q, seq_k // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, SUBLANES, seq_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, scale: float, causal: bool):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)
    num_kv = pl.num_programs(2)
    q_start = qi * block_q
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    live = (not causal) or (k_start < q_start + block_q)

    @pl.when(jnp.asarray(live))
    def _compute():
        # bf16 MXU operands, fp32 stats/accumulator (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]  # stats replicated over sublane dim
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc_ref[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(j == num_kv - 1)
    def _finish():
        dq_ref[0] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_acc_ref, dv_acc_ref, *, scale: float, causal: bool):
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    ki = pl.program_id(1)
    i = pl.program_id(2)
    num_q = pl.num_programs(2)
    k_start = ki * block_k
    q_start = i * block_q

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    # causal: this k block only receives grads from q rows >= k_start
    live = (not causal) or (q_start + block_q > k_start)

    @pl.when(jnp.asarray(live))
    def _compute():
        # bf16 MXU operands, fp32 stats/accumulators (see _fwd_kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk)
        p_lo = p.astype(do.dtype)
        dv_acc_ref[:] += jax.lax.dot_general(p_lo, do, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _finish():
        # q is unscaled in the s recompute, so dk picks up the scale here
        dk_ref[0] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, *, causal: bool, scale: float, block_q: int,
               block_k: int):
    bh, seq_q, d = q.shape
    _, seq_k, _ = k.shape
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # sublane-replicated stats layout (see SUBLANES note at the top)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, SUBLANES, seq_q))

    grid_q = (bh, seq_q // block_q, seq_k // block_k)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal),
        grid=grid_q,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    grid_k = (bh, seq_k // block_k, seq_q // block_q)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal),
        grid=grid_k,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_q), lambda b, j, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_q), lambda b, j, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale, block_q=block_q,
                        block_k=block_k)
    return out


def _flash_attention_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, causal=causal, scale=scale, block_q=block_q,
                          block_k=block_k)
    # Name the kernel outputs so activation-checkpoint policies can save
    # them: under the "dots" policy alone a rematerialized block re-runs the
    # whole forward kernel in the backward pass (pallas_call outputs are not
    # dot_general outputs). remat_policy="dots" composes
    # save_only_these_names(*ATTN_SAVE_NAMES) on top, which keeps (out, lse)
    # and skips the recompute; q/k/v re-derive cheaply from the saved qkv
    # projection dot.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal=causal, scale=scale,
                            block_q=block_q, block_k=block_k)
    return dq, dk, dv


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def auto_block_sizes(seq: int) -> "tuple[int, int]":
    """(block_q, block_k) tuned on v5e with bf16 MXU operands (round-5
    sweep, benchmarks/flash1k_sweep_results.json + the r2 crossover table):
    512x1024 wins at 1024-4096; the biggest tiles win at >=8192. Each block
    is shrunk (halved) until it divides ``seq`` — the kernel requires exact
    tiling, and an odd seq must not crash the auto path."""
    if seq >= 8192:
        bq, bk = 1024, 1024
    elif seq >= 1024:
        bq, bk = 512, 1024
    else:
        bq, bk = DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    while bq > 1 and seq % bq != 0:
        bq //= 2
    while bk > 1 and seq % bk != 0:
        bk //= 2
    return bq, bk


def use_flash_by_default(seq: int) -> bool:
    """Shape-based auto-selection: with bf16 MXU operands (round 5) the
    Pallas kernel beats XLA's fused attention from seq 1024 up on TPU
    (1.55x @1k, 1.33x @2k — benchmarks/flash1k_sweep_results.json; 2x+ at
    4k-8k, BASELINE.md crossover table); below that XLA wins. Off-TPU
    (interpret mode) it is only for tests. Shapes whose auto blocks would
    degenerate (seq with a tiny power-of-two factor) stay on XLA."""
    import jax

    return jax.default_backend() == "tpu" and seq >= 1024 \
        and min(auto_block_sizes(seq)) >= 128


def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Fused attention. q/k/v: (batch, seq, heads, head_dim) → same-shape out.

    ``scale`` defaults to 1/sqrt(head_dim); block sizes default to the
    seq-tuned table (``auto_block_sizes``).
    """
    b, t, h, d = q.shape
    _, s, _, _ = k.shape
    if causal and t != s:
        raise ValueError(
            f"causal flash attention requires seq_q == seq_k (got {t} vs {s});"
            " the mask assumes aligned positions. Use causal=False for"
            " cross-attention.")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # Derive block_q from t and block_k from s independently — the kernel
    # requires t % block_q == 0 and s % block_k == 0, and t != s (non-causal
    # cross-attention; causal masking assumes aligned q/k positions, so
    # causal t != s is not supported) would otherwise pick blocks tuned for
    # one length that fail to divide the other.
    auto_q, _ = auto_block_sizes(t)
    _, auto_k = auto_block_sizes(s)
    block_q = auto_q if block_q is None else block_q
    block_k = auto_k if block_k is None else block_k

    # (B, T, H, D) → (B*H, T, D)
    def to_bh(x, T):
        return x.transpose(0, 2, 1, 3).reshape(b * h, T, d)

    out = _flash_attention(to_bh(q, t), to_bh(k, s), to_bh(v, s), causal, scale,
                           block_q, block_k)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def mha_reference(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Plain jnp attention for kernel equivalence tests (the analog of the
    reference's kernel-vs-PyTorch numerics tests, tests/unit/ops/transformer)."""
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, k.shape[1]), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
