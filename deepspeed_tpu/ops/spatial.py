"""Spatial (diffusers) fused bias ops.

Capability parity with reference ``csrc/spatial/csrc/opt_bias_add.cu`` +
``pt_binding.cpp:109-111`` (``nhwc_bias_add``, ``nhwc_bias_add_add``,
``nhwc_bias_add_bias_add``) — the UNet/VAE hot elementwise ops. On TPU
these are jnp expressions: XLA fuses them into the surrounding convs (the
fusion the reference does by hand in CUDA), so the parity surface is the
op vocabulary + NHWC layout contract, not a custom kernel.
"""

from __future__ import annotations

import jax.numpy as jnp


def nhwc_bias_add(activation: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """activation (N, H, W, C) + bias (C,)."""
    return activation + bias.reshape((1,) * (activation.ndim - 1) + (-1,))


def nhwc_bias_add_add(activation: jnp.ndarray, bias: jnp.ndarray,
                      other: jnp.ndarray) -> jnp.ndarray:
    """(activation + bias) + other — the residual-add variant."""
    return nhwc_bias_add(activation, bias) + other


def nhwc_bias_add_bias_add(activation: jnp.ndarray, bias: jnp.ndarray,
                           other: jnp.ndarray,
                           other_bias: jnp.ndarray) -> jnp.ndarray:
    """(activation + bias) + (other + other_bias) — two biased branches."""
    return nhwc_bias_add(activation, bias) + nhwc_bias_add(other, other_bias)
