"""Fused LayerNorm -> Linear as a Pallas TPU kernel (forward + backward).

The TPU piece of the reference's fused transformer-block kernel
(csrc/transformer/ds_transformer_cuda.cpp:1055 norm_layer_fwd/bwd chains):
XLA fuses elementwise epilogues into matmuls but cannot fuse a
reduction->broadcast chain (LayerNorm) into a dot operand, so the
normalized activation makes a full HBM round-trip per LN->matmul pair
(twice per transformer block: ln_1->qkv, ln_2->fc), and the backward pays
the same for `dnorm = dy @ W^T` before the LayerNorm backward.

This kernel keeps the normalized tile in VMEM:

* forward: one grid row per (M-tile); at the first N-step the kernel
  computes fp32 row statistics, normalizes, applies (gamma, beta) and
  caches the normalized tile in VMEM scratch; every N-step then runs the
  MXU dot straight off that scratch. `y = (LN(x) * gamma + beta) @ W + b`
  never materializes LN(x) in HBM. Row stats (mean, rstd) are emitted for
  the backward.
* backward dx: `dn` accumulates in VMEM across the N-axis grid
  (`dn += dy_tile @ W_tile^T`); the final step applies the LayerNorm
  backward in-kernel and writes `dx` plus per-M-tile partial (dgamma,
  dbeta) rows — `dn` never reaches HBM.
* backward dW/db ride XLA: `n` is recomputed elementwise from the saved
  stats (one materialization in the backward only, same as the unfused
  path's remat) and fed to a standard dot.

Stats use the lse layout convention from ops/attention/flash_attention.py:
(SUBLANES, M) with values replicated across the sublane dim.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SUBLANES = 8
# checkpoint_name tags (see ops/attention/flash_attention.py ATTN_SAVE_NAMES):
# saving (y, stats) lets the "dots" remat policy skip re-running the fused
# forward kernel in the backward pass
LN_SAVE_NAMES = ("ln_linear_out", "ln_linear_stats")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, g_ref, b_ref, w_ref, bias_ref, y_ref, mean_ref,
                rstd_ref, n_ref, *, eps: float):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _stats():
        xf = x_ref[...].astype(jnp.float32)
        mu = jnp.mean(xf, axis=1, keepdims=True)
        xc = xf - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
        xh = xc * rstd
        g = g_ref[...].astype(jnp.float32)
        b = b_ref[...].astype(jnp.float32)
        n_ref[...] = (xh * g + b).astype(n_ref.dtype)
        mean_ref[...] = jnp.broadcast_to(mu[:, 0][None, :], mean_ref.shape)
        rstd_ref[...] = jnp.broadcast_to(rstd[:, 0][None, :], rstd_ref.shape)

    acc = jax.lax.dot_general(n_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    y_ref[...] = (acc + bias_ref[...].astype(jnp.float32)).astype(y_ref.dtype)


def _bwd_dx_kernel(dy_ref, w_ref, x_ref, g_ref, mean_ref, rstd_ref, dx_ref,
                   dg_ref, db_ref, dn_ref):
    j = pl.program_id(1)
    num_n = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        dn_ref[...] = jnp.zeros_like(dn_ref)

    dn_ref[...] += jax.lax.dot_general(
        dy_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == num_n - 1)
    def _finish():
        dn = dn_ref[...]
        xf = x_ref[...].astype(jnp.float32)
        mu = mean_ref[0][:, None]
        rstd = rstd_ref[0][:, None]
        xh = (xf - mu) * rstd
        g = g_ref[...].astype(jnp.float32)
        dxh = dn * g
        m1 = jnp.mean(dxh, axis=1, keepdims=True)
        m2 = jnp.mean(dxh * xh, axis=1, keepdims=True)
        dx_ref[...] = (rstd * (dxh - m1 - xh * m2)).astype(dx_ref.dtype)
        # per-M-tile partials, replicated across the 8-sublane dim (a
        # (1, C) block violates Mosaic's sublane-divisibility rule)
        dg_ref[...] = jnp.broadcast_to(
            jnp.sum(dn * xh, axis=0, keepdims=True), dg_ref.shape)
        db_ref[...] = jnp.broadcast_to(
            jnp.sum(dn, axis=0, keepdims=True), db_ref.shape)


def _pick_block(size: int, prefer: int) -> Optional[int]:
    b = prefer
    while b >= 8:
        if size % b == 0:
            return b
        b //= 2
    return None


def _ln_linear_fwd_impl(x, gamma, beta, w, bias, *, eps, block_m, block_n):
    m, c = x.shape
    n = w.shape[1]
    grid = (m // block_m, n // block_n)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, block_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, block_m), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, block_m), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((SUBLANES, m), jnp.float32),
            jax.ShapeDtypeStruct((SUBLANES, m), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_m, c), x.dtype)],
        interpret=_interpret(),
    )(x, gamma.reshape(1, c), beta.reshape(1, c), w, bias.reshape(1, n))
    return y, mean, rstd


def _ln_linear_bwd_impl(x, gamma, mean, rstd, w, dy, *, block_m, block_n):
    m, c = x.shape
    n = w.shape[1]
    grid = (m // block_m, n // block_n)
    dx, dg_parts, db_parts = pl.pallas_call(
        _bwd_dx_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, block_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, block_m), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, block_m), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_m, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANES, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((m // block_m * SUBLANES, c), jnp.float32),
            jax.ShapeDtypeStruct((m // block_m * SUBLANES, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_m, c), jnp.float32)],
        interpret=_interpret(),
    )(dy, w, x, gamma.reshape(1, c), mean, rstd)
    return dx, dg_parts, db_parts


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _ln_linear(x, gamma, beta, w, bias, eps, block_m, block_n):
    y, _, _ = _ln_linear_fwd_impl(x, gamma, beta, w, bias, eps=eps,
                                  block_m=block_m, block_n=block_n)
    return y


def _ln_linear_vjp_fwd(x, gamma, beta, w, bias, eps, block_m, block_n):
    from jax.ad_checkpoint import checkpoint_name

    y, mean, rstd = _ln_linear_fwd_impl(x, gamma, beta, w, bias, eps=eps,
                                        block_m=block_m, block_n=block_n)
    y = checkpoint_name(y, "ln_linear_out")
    mean = checkpoint_name(mean, "ln_linear_stats")
    rstd = checkpoint_name(rstd, "ln_linear_stats")
    return y, (x, gamma, beta, mean, rstd, w)


def _ln_linear_vjp_bwd(eps, block_m, block_n, res, dy):
    x, gamma, beta, mean, rstd, w = res
    dx, dg_parts, db_parts = _ln_linear_bwd_impl(
        x, gamma, mean, rstd, w, dy, block_m=block_m, block_n=block_n)
    # parts are replicated over the sublane dim: take row 0 of each tile
    c = x.shape[1]
    dgamma = dg_parts.reshape(-1, SUBLANES, c)[:, 0].sum(0).astype(
        gamma.dtype)
    dbeta = db_parts.reshape(-1, SUBLANES, c)[:, 0].sum(0).astype(
        beta.dtype)
    # dW/db on XLA: recompute n elementwise from the saved stats (one
    # backward-only materialization, same cost the unfused remat pays)
    xf = x.astype(jnp.float32)
    xh = (xf - mean[0][:, None]) * rstd[0][:, None]
    nmat = (xh * gamma.astype(jnp.float32) +
            beta.astype(jnp.float32)).astype(x.dtype)
    dw = jax.lax.dot_general(nmat, dy, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    db = dy.astype(jnp.float32).sum(0)
    return dx, dgamma, dbeta, dw.astype(w.dtype), db.astype(dy.dtype)


_ln_linear.defvjp(_ln_linear_vjp_fwd, _ln_linear_vjp_bwd)


def _prefer_block_m(c: int) -> int:
    """VMEM budget: the backward carries an fp32 (block_m, C) accumulator
    plus bf16 x/W tiles, so block_m shrinks as C grows."""
    if c <= 1024:
        return 512
    if c <= 2048:
        return 256
    return 128


def supports_fused(m: int, c: int, n: int) -> bool:
    """Shape gate for the fused path: exact tiling with MXU-sized blocks and
    a VMEM budget that holds a (block_m, C) tile (C <= 4096)."""
    bm = _pick_block(m, _prefer_block_m(c))
    bn = _pick_block(n, 512)
    return (c <= 4096 and c % 128 == 0 and
            bm is not None and bn is not None and bn >= 128)


def ln_linear(x, gamma, beta, w, bias, *, eps: float = 1e-5):
    """``(LN(x; gamma, beta) @ w + bias)`` fused; x: (..., C) -> (..., N).

    Falls back to the plain XLA composition when the shape gate fails
    (ragged M/N, very wide C) — numerics match either way.
    """
    *lead, c = x.shape
    n = w.shape[1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, c)
    if not supports_fused(m, c, n):
        xf = x2.astype(jnp.float32)
        mu = jnp.mean(xf, axis=1, keepdims=True)
        xc = xf - mu
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
        xh = xc * jax.lax.rsqrt(var + eps)
        nmat = (xh * gamma.astype(jnp.float32) +
                beta.astype(jnp.float32)).astype(x.dtype)
        # cast w to the activation dtype — fp32 params must not promote
        # the matmul (matches nn.Dense(dtype=...) and the fused kernel)
        y = nmat @ w.astype(x.dtype) + bias.astype(x.dtype)
        return y.reshape(*lead, n)
    block_m = _pick_block(m, _prefer_block_m(c))
    block_n = _pick_block(n, 512)
    y = _ln_linear(x2, gamma.astype(x.dtype), beta.astype(x.dtype),
                   w.astype(x.dtype), bias.astype(x.dtype), eps, block_m,
                   block_n)
    return y.reshape(*lead, n)
