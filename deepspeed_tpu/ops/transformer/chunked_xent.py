"""Streaming (chunked) softmax cross-entropy over a large vocabulary.

The LM loss tail is the single largest activation in training: the
logits tensor is (B, T, V) — at GPT-2 Large scale (mbs 2, T 1024,
V 50257) that is ~400 MB fp32 PER COPY, and the forward + softmax +
backward chain holds several copies, adding GBs of peak HBM. This is
what kept the 774M single-chip row on full remat: selective ("dots")
remat missed fitting by ~0.6 GB (BASELINE.md 774M section).

This module computes the same masked mean cross-entropy WITHOUT ever
materializing the full logits: positions stream through in chunks of
``chunk_size``; each chunk projects onto the vocabulary, reduces to
(logsumexp - target logit) * mask, and is summed. ``jax.checkpoint``
on the chunk body makes the backward rematerialize each chunk's logits
in turn, so peak memory is O(B * chunk_size * V) in both passes.

The per-position math is IDENTICAL to the dense path (the projection
runs in the model's compute dtype, exactly like flax ``Embed.attend`` /
the fp32 lm_head; reductions in fp32) — only the summation order
differs, so losses match to fp32 round-off and gradients to matching
tolerance (parity-tested in tests/unit/models/test_chunked_xent.py).

The reference has no analog (its fused softmax-xent kernels still
materialize logits); this is TPU-native memory engineering in the
spirit of its fused-loss CUDA kernels
(csrc/transformer/general_kernels.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_xent(x, w, targets, mask, chunk_size: int,
                         compute_dtype=jnp.float32):
    """Masked cross-entropy summed over positions, streaming over T.

    Args:
      x: (B, T, C) final hidden states (pre-projection).
      w: (V, C) projection matrix — the tied embedding table, or the
        lm_head kernel transposed.
      targets: (B, T) int32 target ids (already causally shifted).
      mask: (B, T) float32 — 0 for ignored positions.
      chunk_size: positions per streamed chunk (clamped to T).
      compute_dtype: dtype of the projection dot (the model's compute
        dtype — bf16 for the tied ``Embed.attend`` path, fp32 for an
        fp32 lm_head), matching the dense path bit-for-bit per chunk.

    Returns the SUM of masked per-position nll (caller divides by the
    mask sum for the mean).
    """
    B, T, C = x.shape
    chunk_size = min(chunk_size, T)
    pad = (-T) % chunk_size
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (T + pad) // chunk_size
    xs = x.reshape(B, n, chunk_size, C).transpose(1, 0, 2, 3)
    ts = targets.reshape(B, n, chunk_size).transpose(1, 0, 2)
    ms = mask.reshape(B, n, chunk_size).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_nll_sum(w, xc, tc, mc):
        logits = jnp.dot(xc.astype(compute_dtype),
                         w.T.astype(compute_dtype))
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return ((lse - tgt) * mc).sum()

    def body(acc, args):
        xc, tc, mc = args
        return acc + chunk_nll_sum(w, xc, tc, mc), None

    loss, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, ms))
    return loss
