from .transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)

__all__ = ["DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig"]
