"""DeepSpeedTransformerLayer — fused training transformer layer API.

Capability parity with reference ``deepspeed/ops/transformer/transformer.py:296
DeepSpeedTransformerLayer`` + ``DeepSpeedTransformerConfig`` (:22) — the
BERT-style fused layer backed by ``csrc/transformer`` (qkv/attn/LN/GeLU/
dropout fused fwd+bwd, tested against the HF BERT layer in
``tests/unit/ops/accelerators/test_accelerator_forward.py``). On TPU the
fusion is the compiler's job: the layer is expressed once in flax and XLA
emits the fused kernels; Pallas flash attention handles the score/softmax
tiling when masks permit. ``pre_layer_norm`` switches post-LN (BERT) vs
pre-LN ordering, mirroring the reference flag.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ...models.bert import BertConfig, BertLayer, BertSelfAttention


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Reference config surface (transformer.py:22). Unused CUDA-specific
    knobs (stochastic_mode, gemm algos) are accepted and ignored."""

    batch_size: int = -1
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False   # memory trick: remat subsumes it
    gelu_checkpoint: bool = False        # ditto
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False  # honored: forward returns (hidden,) when set
    training: bool = True


class DeepSpeedTransformerLayer(nn.Module):
    """Drop-in fused layer: ``__call__(hidden_states, attention_mask)``
    with (B, T, H) activations, post-LN (BERT) or pre-LN ordering."""

    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic: Optional[bool] = None):
        cfg = self.config
        deterministic = (not cfg.training) if deterministic is None \
            else deterministic
        dtype = jnp.float16 if cfg.fp16 else jnp.float32
        bert_cfg = BertConfig(
            hidden_size=cfg.hidden_size,
            num_attention_heads=cfg.heads,
            intermediate_size=cfg.intermediate_size,
            hidden_dropout_prob=cfg.hidden_dropout_ratio,
            attention_probs_dropout_prob=cfg.attn_dropout_ratio,
            layer_norm_eps=cfg.layer_norm_eps,
            dtype=dtype,
        )
        mask_bias = None
        if attention_mask is not None:
            m = attention_mask
            if m.ndim == 2:
                m = m[:, None, None, :]
            mask_bias = jnp.where(m > 0, 0.0, -1e9).astype(jnp.float32)

        def result(out):
            # reference return_tuple semantics (transformer.py:296 forward
            # returns (hidden_states,) when set)
            return (out,) if cfg.return_tuple else out

        if not cfg.pre_layer_norm:
            # post-LN (original BERT ordering) — exactly BertLayer
            return result(BertLayer(bert_cfg, name="layer")(
                hidden_states, mask_bias, deterministic))

        # pre-LN ordering (reference pre_layer_norm=True)
        x = hidden_states
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                           name="input_ln")
        attn = BertSelfAttention(bert_cfg, name="attention")(
            ln1(x), mask_bias, deterministic)
        if cfg.hidden_dropout_ratio > 0 and not deterministic:
            attn = nn.Dropout(cfg.hidden_dropout_ratio)(
                attn, deterministic=False)
        x = x + attn
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype,
                           name="output_ln")
        y = nn.Dense(cfg.intermediate_size, dtype=dtype,
                     name="intermediate")(ln2(x))
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=dtype, name="output")(y)
        if cfg.hidden_dropout_ratio > 0 and not deterministic:
            y = nn.Dropout(cfg.hidden_dropout_ratio)(y, deterministic=False)
        return result(x + y)
