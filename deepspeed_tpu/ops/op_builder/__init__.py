"""Lazy native-op build system (≅ reference ``op_builder/builder.py:102
OpBuilder`` JIT-load contract, radically smaller).

The reference JIT-compiles torch CUDA extensions per op at first use
(builder.py:443). Here the native surface is two host-side C++ libraries
(CPU Adam, AIO) compiled with g++ to plain shared objects and bound with
ctypes — no pybind11/torch toolchain. Pallas kernels need no building.

``OpBuilder.load()`` compiles on first use into ``_build/`` next to this
file (keyed by source mtime) and returns a ``ctypes.CDLL``. Failures mark
the builder incompatible (``is_compatible()`` → False) so callers can fall
back to pure-numpy paths — the analog of the reference's compatibility
probes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(__file__), "..", "csrc")
_BUILD = os.path.join(os.path.dirname(__file__), "..", "_build")


class OpBuilder:
    NAME = "base"
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []

    _cache = {}

    def absolute_sources(self) -> List[str]:
        return [os.path.normpath(os.path.join(_CSRC, s)) for s in self.SOURCES]

    def so_path(self) -> str:
        return os.path.join(_BUILD, f"{self.NAME}.so")

    def _stale(self) -> bool:
        so = self.so_path()
        if not os.path.exists(so):
            return True
        so_mtime = os.path.getmtime(so)
        return any(os.path.getmtime(s) > so_mtime for s in self.absolute_sources())

    def build(self) -> str:
        os.makedirs(_BUILD, exist_ok=True)
        so = self.so_path()
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
                "-march=native"] + self.EXTRA_FLAGS
               + self.absolute_sources() + ["-o", so])
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            # -march=native can be unsupported in exotic environments; retry
            stderr = getattr(e, "stderr", str(e))
            try:
                cmd = [c for c in cmd if c != "-march=native"]
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except Exception:
                raise RuntimeError(
                    f"building native op {self.NAME} failed:\n{stderr}") from e
        return so

    def is_compatible(self) -> bool:
        try:
            self.load()
            return True
        except Exception:
            return False

    def load(self) -> ctypes.CDLL:
        if self.NAME in OpBuilder._cache:
            return OpBuilder._cache[self.NAME]
        if os.environ.get("DS_SKIP_NATIVE_BUILD"):
            raise RuntimeError("native builds disabled by DS_SKIP_NATIVE_BUILD")
        if self._stale():
            logger.info(f"building native op {self.NAME} ...")
            self.build()
        lib = ctypes.CDLL(self.so_path())
        self._declare(lib)
        OpBuilder._cache[self.NAME] = lib
        return lib

    def _declare(self, lib: ctypes.CDLL) -> None:
        """Subclasses set argtypes/restypes here."""


class CPUAdamBuilder(OpBuilder):
    """≅ reference op_builder/cpu_adam.py."""

    NAME = "ds_cpu_adam"
    SOURCES = ["cpu_adam.cpp"]

    def _declare(self, lib):
        f32p = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.ds_adam_step.argtypes = [
            f32p, f32p, f32p, f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float]
        lib.ds_adam_step.restype = None
        lib.ds_adagrad_step.argtypes = [
            f32p, f32p, f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.ds_adagrad_step.restype = None
        lib.ds_f32_to_bf16.argtypes = [u16p, f32p, ctypes.c_int64]
        lib.ds_f32_to_bf16.restype = None
        lib.ds_has_nonfinite.argtypes = [f32p, ctypes.c_int64]
        lib.ds_has_nonfinite.restype = ctypes.c_int


class AsyncIOBuilder(OpBuilder):
    """≅ reference op_builder/async_io.py:12."""

    NAME = "ds_aio"
    SOURCES = ["aio.cpp"]

    def _declare(self, lib):
        lib.ds_aio_create.argtypes = [ctypes.c_int, ctypes.c_int64,
                                      ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_destroy.restype = None
        for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = ctypes.c_int64  # completion ticket
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        lib.ds_aio_wait.restype = ctypes.c_int64
        lib.ds_aio_wait_ticket.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ds_aio_wait_ticket.restype = ctypes.c_int64
        lib.ds_aio_pending.argtypes = [ctypes.c_void_p]
        lib.ds_aio_pending.restype = ctypes.c_int64
        lib.ds_aio_probe_o_direct.argtypes = [ctypes.c_char_p]
        lib.ds_aio_probe_o_direct.restype = ctypes.c_int


ALL_OPS = {
    CPUAdamBuilder.NAME: CPUAdamBuilder,
    AsyncIOBuilder.NAME: AsyncIOBuilder,
}


def available_ops():
    """{op name: built/compatible} — feeds ds_report (env_report.py)."""
    out = {}
    for name, cls in ALL_OPS.items():
        out[name] = cls().is_compatible()
    # Pallas kernels need no building; report them by import health
    try:
        from ..attention import flash_attention  # noqa: F401

        out["pallas_flash_attention"] = True
    except Exception:
        out["pallas_flash_attention"] = False
    try:
        from ..sparse_attention import sparse_self_attention  # noqa: F401

        out["pallas_sparse_attention"] = True
    except Exception:
        out["pallas_sparse_attention"] = False
    return out
