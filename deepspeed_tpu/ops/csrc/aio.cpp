// Async file I/O for the NVMe offload tier (ZeRO-Infinity swap).
//
// TPU-native equivalent of the reference's csrc/aio/ library
// (deepspeed_aio_thread_t work/complete queues, deepspeed_py_aio_handle
// async_pread/async_pwrite/wait, O_DIRECT + block_size + queue_depth
// config). Design:
//
// * every request is SPLIT into block_size chunks fanned across the worker
//   thread pool — one large swap read/write saturates the device with
//   queue-depth parallel chunk I/Os (the role libaio iodepth plays in the
//   reference);
// * O_DIRECT (optional): chunks whose (offset, size, buffer address) are
//   all 4096-aligned go through an O_DIRECT fd, bypassing the page cache —
//   the reference's alignment contract (csrc/aio/common/); misaligned
//   chunks (tails, odd buffers) fall back to the buffered fd of the same
//   file;
// * queue_depth bounds the number of queued chunks — submit blocks when
//   the queue is full (backpressure instead of unbounded memory).
//
// C ABI, ctypes-bound.

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kAlign = 4096;

struct FileHandles {
  int fd_buffered = -1;
  int fd_direct = -1;
  ~FileHandles() {
    if (fd_buffered >= 0) ::close(fd_buffered);
    if (fd_direct >= 0) ::close(fd_direct);
  }
};

struct Request {
  bool write;
  std::shared_ptr<FileHandles> files;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  int64_t ticket = 0;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  std::condition_variable cv_space;
  int64_t inflight = 0;
  int64_t completed = 0;
  int64_t block_size = 1 << 20;
  int64_t queue_limit = 0;  // 0 = unbounded
  bool o_direct = false;
  std::atomic<int64_t> errors{0};
  bool shutdown = false;
  // per-request ("ticket") completion tracking: remaining chunk count +
  // failed chunk count, so callers can wait on ONE request (the
  // pipelined swap-in path) without draining the whole queue
  int64_t next_ticket = 1;
  std::unordered_map<int64_t, int64_t> ticket_remaining;
  std::unordered_map<int64_t, int64_t> ticket_errors;
  // DS_AIO_SIM_US_PER_MB: simulated device latency (test/bench-only) —
  // each chunk sleeps nbytes-proportionally while holding the "device"
  // mutex of its direction, so the simulated bandwidth is aggregate across
  // threads (a real device's queue), full-duplex (NVMe reads and writes
  // proceed concurrently), and the sleeping thread genuinely yields the CPU
  int64_t sim_us_per_mb = 0;
  std::mutex sim_mu_read, sim_mu_write;

  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        req = std::move(queue.front());
        queue.pop_front();
        cv_space.notify_all();
      }
      if (sim_us_per_mb > 0) {
        std::unique_lock<std::mutex> dev(req.write ? sim_mu_write
                                                   : sim_mu_read);
        int64_t us = req.nbytes * sim_us_per_mb / (1 << 20);
        if (us > 0) ::usleep(static_cast<useconds_t>(us));
      }
      bool ok = run_one(req);
      if (!ok) errors.fetch_add(1);
      {
        std::unique_lock<std::mutex> lock(mu);
        --inflight;
        ++completed;
        if (!ok) ++ticket_errors[req.ticket];
        auto it = ticket_remaining.find(req.ticket);
        if (it != ticket_remaining.end() && --it->second == 0)
          cv_done.notify_all();
        if (inflight == 0) cv_done.notify_all();
      }
    }
  }

  static bool aligned(const Request& req) {
    return req.offset % kAlign == 0 && req.nbytes % kAlign == 0 &&
           reinterpret_cast<uintptr_t>(req.buf) % kAlign == 0;
  }

  static bool run_one(const Request& req) {
    int fd = (req.files->fd_direct >= 0 && aligned(req))
                 ? req.files->fd_direct
                 : req.files->fd_buffered;
    if (fd < 0) return false;
    char* p = static_cast<char*>(req.buf);
    int64_t left = req.nbytes;
    int64_t off = req.offset;
    while (left > 0) {
      ssize_t r = req.write ? ::pwrite64(fd, p, left, off)
                            : ::pread64(fd, p, left, off);
      if (r <= 0) return false;
      p += r;
      off += r;
      left -= r;
    }
    return true;
  }
};

}  // namespace

extern "C" {

// block_size: chunking granularity (bytes, >= 4096); queue_depth: max
// queued chunks (0 = unbounded); o_direct: route aligned chunks through
// O_DIRECT.
void* ds_aio_create(int num_threads, int64_t block_size, int64_t queue_depth,
                    int o_direct) {
  auto* h = new Handle();
  if (num_threads < 1) num_threads = 1;
  if (block_size >= 4096) h->block_size = block_size;
  h->queue_limit = queue_depth > 0 ? queue_depth : 0;
  h->o_direct = o_direct != 0;
  if (const char* sim = ::getenv("DS_AIO_SIM_US_PER_MB"))
    h->sim_us_per_mb = ::strtoll(sim, nullptr, 10);
  for (int i = 0; i < num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  return h;
}

void ds_aio_destroy(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  {
    std::unique_lock<std::mutex> lock(h->mu);
    h->shutdown = true;
  }
  h->cv_work.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

static int64_t submit(Handle* h, bool write, const char* path, void* buf,
                      int64_t nbytes, int64_t offset) {
  auto files = std::make_shared<FileHandles>();
  int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  files->fd_buffered = ::open(path, flags, 0644);
#ifdef O_DIRECT
  if (h->o_direct) files->fd_direct = ::open(path, flags | O_DIRECT, 0644);
#endif
  // register the ticket with its FULL chunk count before pushing any chunk
  // (a fast worker must not see remaining hit 0 mid-submission)
  int64_t n_chunks = nbytes == 0 ? 1 : (nbytes + h->block_size - 1) / h->block_size;
  int64_t ticket;
  {
    std::unique_lock<std::mutex> lock(h->mu);
    ticket = h->next_ticket++;
    h->ticket_remaining[ticket] = n_chunks;
  }
  if (nbytes == 0) {
    std::unique_lock<std::mutex> lock(h->mu);
    h->ticket_remaining[ticket] = 0;
    h->cv_done.notify_all();
    return ticket;
  }
  // split into block_size chunks; each chunk is an independent queue entry
  int64_t pos = 0;
  do {
    int64_t len = nbytes - pos < h->block_size ? nbytes - pos : h->block_size;
    Request req{write, files, static_cast<char*>(buf) + pos, len,
                offset + pos, ticket};
    {
      std::unique_lock<std::mutex> lock(h->mu);
      h->cv_space.wait(lock, [&] {
        return h->queue_limit == 0 ||
               static_cast<int64_t>(h->queue.size()) < h->queue_limit;
      });
      h->queue.push_back(std::move(req));
      ++h->inflight;
    }
    h->cv_work.notify_one();
    pos += len;
  } while (pos < nbytes);
  return ticket;
}

int64_t ds_aio_pread(void* handle, const char* path, void* buf, int64_t nbytes,
                     int64_t offset) {
  return submit(static_cast<Handle*>(handle), false, path, buf, nbytes,
                offset);
}

int64_t ds_aio_pwrite(void* handle, const char* path, const void* buf,
                      int64_t nbytes, int64_t offset) {
  return submit(static_cast<Handle*>(handle), true, path,
                const_cast<void*>(buf), nbytes, offset);
}

// Blocks until ONE request (ticket) completes; returns its failed-chunk
// count (0 = success). The ticket is forgotten afterwards.
int64_t ds_aio_wait_ticket(void* handle, int64_t ticket) {
  auto* h = static_cast<Handle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  auto done = [&] {
    auto it = h->ticket_remaining.find(ticket);
    return it == h->ticket_remaining.end() || it->second == 0;
  };
  h->cv_done.wait(lock, done);
  h->ticket_remaining.erase(ticket);
  auto it = h->ticket_errors.find(ticket);
  int64_t errs = it == h->ticket_errors.end() ? 0 : it->second;
  h->ticket_errors.erase(ticket);
  return errs;
}

// Blocks until all submitted requests complete. Returns the number of
// failed chunks since the last wait (0 = success).
int64_t ds_aio_wait(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  h->cv_done.wait(lock, [&] { return h->inflight == 0; });
  // everything is complete — drop per-ticket bookkeeping (callers mixing
  // wait()/wait_ticket() would otherwise leak map entries)
  h->ticket_remaining.clear();
  h->ticket_errors.clear();
  return h->errors.exchange(0);
}

int64_t ds_aio_pending(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  return h->inflight;
}

// 1 when the filesystem holding `path` accepts O_DIRECT opens (tmpfs and
// some network filesystems return EINVAL, in which case chunks silently
// use the buffered fd) — lets callers report o_direct_effective honestly.
int ds_aio_probe_o_direct(const char* path) {
#ifdef O_DIRECT
  // O_DIRECT opens are only valid on regular files (a directory open with
  // O_DIRECT fails with EINVAL even on filesystems that support it), so
  // probe with a scratch file when given a directory.
  struct stat st;
  if (::stat(path, &st) == 0 && S_ISDIR(st.st_mode)) {
    std::string probe = std::string(path) + "/.ds_odirect_probe";
    int fd = ::open(probe.c_str(), O_WRONLY | O_CREAT | O_DIRECT, 0644);
    if (fd >= 0) {
      ::close(fd);
      ::unlink(probe.c_str());
      return 1;
    }
    ::unlink(probe.c_str());
    return 0;
  }
  int fd = ::open(path, O_RDONLY | O_DIRECT);
  if (fd >= 0) {
    ::close(fd);
    return 1;
  }
#endif
  return 0;
}

}  // extern "C"
