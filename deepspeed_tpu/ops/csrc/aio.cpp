// Async file I/O for the NVMe offload tier (ZeRO-Infinity swap).
//
// TPU-native equivalent of the reference's csrc/aio/ library: a worker
// thread pool draining a request queue of pread/pwrite jobs against local
// SSD, with a wait() barrier — the same handle contract as
// deepspeed_aio_thread_t (csrc/aio/py_lib/deepspeed_aio_thread.h:41) and
// deepspeed_py_aio_handle (async_pread/async_pwrite/wait). Plain
// pread64/pwrite64 on buffered fds instead of libaio+O_DIRECT: TPU-VM local
// SSD sustains its bandwidth through the page cache, and the queue-depth
// parallelism comes from the thread count.
//
// C ABI, ctypes-bound.

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Request {
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  int64_t inflight = 0;
  int64_t completed = 0;
  std::atomic<int64_t> errors{0};
  bool shutdown = false;

  void worker_loop() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        req = std::move(queue.front());
        queue.pop_front();
      }
      if (!run_one(req)) errors.fetch_add(1);
      {
        std::unique_lock<std::mutex> lock(mu);
        --inflight;
        ++completed;
        if (inflight == 0) cv_done.notify_all();
      }
    }
  }

  static bool run_one(const Request& req) {
    int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return false;
    char* p = static_cast<char*>(req.buf);
    int64_t left = req.nbytes;
    int64_t off = req.offset;
    bool ok = true;
    while (left > 0) {
      ssize_t r = req.write ? ::pwrite64(fd, p, left, off)
                            : ::pread64(fd, p, left, off);
      if (r <= 0) {
        ok = false;
        break;
      }
      p += r;
      off += r;
      left -= r;
    }
    ::close(fd);
    return ok;
  }
};

}  // namespace

extern "C" {

void* ds_aio_create(int num_threads) {
  auto* h = new Handle();
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  return h;
}

void ds_aio_destroy(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  {
    std::unique_lock<std::mutex> lock(h->mu);
    h->shutdown = true;
  }
  h->cv_work.notify_all();
  for (auto& t : h->workers) t.join();
  delete h;
}

static void submit(Handle* h, bool write, const char* path, void* buf,
                   int64_t nbytes, int64_t offset) {
  {
    std::unique_lock<std::mutex> lock(h->mu);
    h->queue.push_back(Request{write, path, buf, nbytes, offset});
    ++h->inflight;
  }
  h->cv_work.notify_one();
}

void ds_aio_pread(void* handle, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
  submit(static_cast<Handle*>(handle), false, path, buf, nbytes, offset);
}

void ds_aio_pwrite(void* handle, const char* path, const void* buf,
                   int64_t nbytes, int64_t offset) {
  submit(static_cast<Handle*>(handle), true, path, const_cast<void*>(buf),
         nbytes, offset);
}

// Blocks until all submitted requests complete. Returns the number of
// failed requests since the last wait (0 = success).
int64_t ds_aio_wait(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  h->cv_done.wait(lock, [&] { return h->inflight == 0; });
  return h->errors.exchange(0);
}

int64_t ds_aio_pending(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  return h->inflight;
}

}  // extern "C"
