// Host-side vectorized Adam/AdamW for offloaded optimizer state.
//
// TPU-native equivalent of the reference's csrc/adam/cpu_adam.cpp (AVX
// SIMD Adam driving ZeRO-Offload, bound by ops/adam/cpu_adam.py:13
// DeepSpeedCPUAdam). Differences by design: no CUDA half-copy path (the
// device copy is a jax device_put of the bf16 view); vectorization is left
// to the compiler (-O3 -march=native + omp simd) instead of hand-written
// intrinsics so the same source serves AVX2/AVX512/NEON hosts.
//
// C ABI (ctypes-bound; no pybind11 in this image):
//   ds_adam_step    — fused m/v/param update over a flat fp32 span
//   ds_f32_to_bf16  — round-to-nearest-even fp32→bf16 copy (device view)
//   ds_has_nonfinite— overflow probe for fp16 loss scaling

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// One fused Adam step over [0, n). Bias corrections are precomputed by the
// caller (bc1 = 1-beta1^t, bc2 = 1-beta2^t; pass 1.0/1.0 to disable).
// adamw != 0 → decoupled weight decay; else L2 added to the gradient.
void ds_adam_step(float* __restrict__ param,
                  const float* __restrict__ grad,
                  float* __restrict__ exp_avg,
                  float* __restrict__ exp_avg_sq,
                  int64_t n,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adamw, float bc1, float bc2) {
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grad[i];
    float p = param[i];
    if (weight_decay != 0.0f && !adamw) g += weight_decay * p;
    float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
    float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float update = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f && adamw) update += weight_decay * p;
    param[i] = p - lr * update;
  }
}

// Adagrad step (≅ csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_step(float* __restrict__ param,
                     const float* __restrict__ grad,
                     float* __restrict__ accum,
                     int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grad[i];
    float p = param[i];
    if (weight_decay != 0.0f) g += weight_decay * p;
    float a = accum[i] + g * g;
    accum[i] = a;
    param[i] = p - lr * g / (std::sqrt(a) + eps);
  }
}

// fp32 → bf16 with round-to-nearest-even (what the device expects).
void ds_f32_to_bf16(uint16_t* __restrict__ dst,
                    const float* __restrict__ src, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &src[i], 4);
    uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
    dst[i] = (uint16_t)((bits + rounding) >> 16);
  }
}

// Returns 1 if any element is NaN/Inf (overflow probe for dynamic loss
// scaling, ≅ _has_inf_or_nan on the CPU-offload path).
int ds_has_nonfinite(const float* __restrict__ x, int64_t n) {
  int bad = 0;
#pragma omp parallel for schedule(static) reduction(|| : bad)
  for (int64_t i = 0; i < n; ++i) {
    bad = bad || !std::isfinite(x[i]);
  }
  return bad ? 1 : 0;
}

}  // extern "C"
