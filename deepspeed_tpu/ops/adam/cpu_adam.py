"""DeepSpeedCPUAdam — host-side Adam over offloaded optimizer state
(≅ reference ``ops/adam/cpu_adam.py:13``, kernel csrc/adam/cpu_adam.cpp).

Operates in place on flat fp32 numpy views of (master, exp_avg, exp_avg_sq),
one call per parameter leaf; the native library parallelizes/vectorizes.
Falls back to a numpy implementation when the native build is unavailable
(``DS_SKIP_NATIVE_BUILD=1`` or no toolchain) — same numerics, slower.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from ..op_builder import CPUAdamBuilder


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    def __init__(self, lr: float = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 adamw_mode: bool = True, bias_correction: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self._lib: Optional[ctypes.CDLL] = None
        try:
            self._lib = CPUAdamBuilder().load()
        except Exception:
            self._lib = None  # numpy fallback

    @property
    def native(self) -> bool:
        return self._lib is not None

    def step(self, param: np.ndarray, grad: np.ndarray, exp_avg: np.ndarray,
             exp_avg_sq: np.ndarray, step_num: int,
             lr: Optional[float] = None) -> None:
        """One Adam step, in place. All arrays: contiguous fp32, same size.
        ``step_num`` is 1-indexed."""
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step_num
            bc2 = 1.0 - b2 ** step_num
        else:
            bc1 = bc2 = 1.0
        if self._lib is not None:
            self._lib.ds_adam_step(
                _f32p(param), _f32p(grad), _f32p(exp_avg), _f32p(exp_avg_sq),
                param.size, lr, b1, b2, self.eps, self.weight_decay,
                int(self.adamw_mode), bc1, bc2)
            return
        # numpy fallback (same math as the kernel)
        g = grad
        if self.weight_decay != 0.0 and not self.adamw_mode:
            g = g + self.weight_decay * param
        exp_avg *= b1
        exp_avg += (1 - b1) * g
        exp_avg_sq *= b2
        exp_avg_sq += (1 - b2) * g * g
        denom = np.sqrt(exp_avg_sq) / np.sqrt(bc2) + self.eps
        update = (exp_avg / bc1) / denom
        if self.weight_decay != 0.0 and self.adamw_mode:
            update = update + self.weight_decay * param
        param -= lr * update

    def has_overflow(self, grad: np.ndarray) -> bool:
        if self._lib is not None:
            return bool(self._lib.ds_has_nonfinite(_f32p(grad), grad.size))
        return not np.isfinite(grad).all()

    def to_bf16(self, src: np.ndarray, dst: Optional[np.ndarray] = None) -> np.ndarray:
        """Round-to-nearest-even fp32→bf16; returns a uint16-backed view
        suitable for jnp.asarray(..., dtype=bfloat16) via ml_dtypes."""
        import ml_dtypes

        if self._lib is not None:
            if dst is None:
                dst = np.empty(src.shape, np.uint16)
            self._lib.ds_f32_to_bf16(
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                _f32p(src), src.size)
            return dst.view(ml_dtypes.bfloat16)
        return src.astype(ml_dtypes.bfloat16)


class DeepSpeedCPUAdagrad:
    """≅ reference ops/adagrad/cpu_adagrad.py:11."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-8,
                 weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        try:
            self._lib = CPUAdamBuilder().load()
        except Exception:
            self._lib = None

    def step(self, param: np.ndarray, grad: np.ndarray, accum: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            self._lib.ds_adagrad_step(_f32p(param), _f32p(grad), _f32p(accum),
                                      param.size, lr, self.eps, self.weight_decay)
            return
        g = grad
        if self.weight_decay != 0.0:
            g = g + self.weight_decay * param
        accum += g * g
        param -= lr * g / (np.sqrt(accum) + self.eps)
