"""Block-sparsity layout configs — the reference's sparsity vocabulary
(``ops/sparse_attention/sparsity_config.py:95,239,411,546,674``: Dense,
Fixed, Variable, BigBird, BSLongformer, LocalSlidingWindow), re-implemented
for the TPU block-sparse attention in ``sparse_self_attention.py``.

A layout is an int32 array (num_heads, num_blocks, num_blocks): entry
[h, i, j] = 1 ⇔ head h's query block i attends to key block j. Layouts are
built host-side in numpy once per sequence length (they are static under
jit). ``attention="unidirectional"`` masks j > i at the block level; the
kernel applies token-level causal masking inside diagonal blocks.
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base (≅ reference sparsity_config.py:18): common fields + helpers."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    @staticmethod
    def _check_attention(attention: str) -> str:
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                f"only \"uni/bi-directional\" attention is supported, got "
                f"{attention!r}")
        return attention

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} must be a multiple of block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), np.int32)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend to all blocks (debug/reference)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """≅ reference FixedSparsityConfig (sparsity_config.py:95): local windows
    of ``num_local_blocks`` + global attention to the last
    ``num_global_blocks`` of each preceding window ("fixed" pattern from the
    Sparse Transformers paper).

    ``num_different_global_patterns`` rotates which sub-block of the window
    is global across heads (requires different_layout_per_head).
    """

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "num_different_global_patterns > 1 requires "
                "different_layout_per_head=True")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                "num_different_global_patterns exceeds available patterns "
                f"({num_local_blocks // num_global_blocks})")
        self._check_attention(attention)
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(H if self.different_layout_per_head else 1):
            # local windows
            for start in range(0, n, L):
                end = min(start + L, n)
                layout[h, start:end, start:end] = 1
            # global columns: pattern index rotates per head
            pat = h % self.num_different_global_patterns
            # in each local window, the pat-th G-sized sub-block (from the
            # end, reference uses the last sub-blocks) is "global"
            for start in range(0, n, L):
                first_g = start + L - (pat + 1) * G
                if first_g < 0:
                    continue
                g0, g1 = first_g, min(first_g + G, n)
                # vertical: the whole column is global (the unidirectional
                # variant is clipped by the tril below; within-window entries
                # are already covered by the local block)
                layout[h, :, g0:g1] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """≅ reference VariableSparsityConfig (sparsity_config.py:239): random
    blocks + variable-size local windows + global blocks from custom indices."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global_block_end_indices length mismatch")
        self._check_attention(attention)
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        rng = random.Random(0)
        for h in range(H if self.different_layout_per_head else 1):
            # variable local windows: cycle through the given sizes
            start = 0
            i = 0
            while start < n:
                w = self.local_window_blocks[min(i, len(self.local_window_blocks) - 1)]
                end = min(start + w, n)
                layout[h, start:end, start:end] = 1
                start = end
                i += 1
            # random blocks
            for _ in range(self.num_random_blocks):
                r, c = rng.randrange(n), rng.randrange(n)
                layout[h, r, c] = 1
            # global blocks
            for gi, idx in enumerate(self.global_block_indices):
                if idx >= n:
                    continue
                end = idx + 1
                if self.global_block_end_indices is not None:
                    end = min(self.global_block_end_indices[gi], n)
                layout[h, :, idx:end] = 1  # vertical
                if self.horizontal_global_attention:
                    layout[h, idx:end, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """≅ reference BigBirdSparsityConfig (sparsity_config.py:411):
    random + sliding-window + global-block pattern."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = self._check_attention(attention)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        w = self.num_sliding_window_blocks // 2
        rng = random.Random(0)
        for h in range(H if self.different_layout_per_head else 1):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = 1  # window
                # random blocks per row (unidirectional: sample from the past,
                # reference samples full row then masks)
                hi = i + 1 if self.attention == "unidirectional" else n
                for _ in range(self.num_random_blocks):
                    layout[h, i, rng.randrange(max(1, hi))] = 1
            g = min(self.num_global_blocks, n)
            layout[h, :, :g] = 1  # global columns
            layout[h, :g, :] = 1  # global rows
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """≅ reference BSLongformerSparsityConfig (sparsity_config.py:546):
    block-sparse Longformer — sliding window + global attention at given
    block indices."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = self._check_attention(attention)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        w = self.num_sliding_window_blocks // 2
        for h in range(H if self.different_layout_per_head else 1):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = 1
            for gi, idx in enumerate(self.global_block_indices):
                if idx >= n:
                    continue
                end = idx + 1
                if self.global_block_end_indices is not None:
                    end = min(self.global_block_end_indices[gi], n)
                layout[h, :, idx:end] = 1  # global columns
                layout[h, idx:end, :] = 1  # global rows
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """≅ reference LocalSlidingWindowSparsityConfig (sparsity_config.py:674):
    pure sliding window."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = self._check_attention(attention)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        H, n, _ = layout.shape
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            lo = max(0, i - w)
            hi = min(n, i + w + 1) if self.attention == "bidirectional" else i + 1
            layout[0, i, lo:hi] = 1
        layout[1:] = layout[0]
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout
