"""Block-sparse self-attention over a sparsity-config layout.

The reference implements this with Triton SDD/DSD block-sparse matmuls +
sparse softmax (``ops/sparse_attention/matmul.py:17,628``, ``softmax.py:224``,
module ``sparse_self_attention.py:12``). The TPU-native shape is a
**gather-based block formulation**: for each (head, query-block) the layout
selects at most M key blocks; those are gathered into a dense
(…, M·block, head_dim) tile and attention runs as ordinary batched matmuls —
large, static-shape MXU work, fully differentiable (XLA emits the scatter
adjoints), with compute O(nq · M · block²) instead of O(T²). Rows gather
real savings when the layout is sparse (M ≪ num_blocks); XLA fuses the
softmax chain exactly as the hand-written Triton softmax does.

Padded gather slots (rows with fewer than M live blocks) point at block 0
and are killed by the mask term.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)

NEG_INF = -1e30


def layout_to_gather_indices(layout: np.ndarray):
    """(H, nq, nk) 0/1 layout → (indices (H, nq, M), valid (H, nq, M)) where
    M = max live blocks over all (head, q-block) rows."""
    H, nq, nk = layout.shape
    counts = layout.sum(-1)
    M = max(1, int(counts.max()))
    idx = np.zeros((H, nq, M), np.int32)
    valid = np.zeros((H, nq, M), bool)
    for h in range(H):
        for i in range(nq):
            js = np.nonzero(layout[h, i])[0]
            idx[h, i, :len(js)] = js
            valid[h, i, :len(js)] = True
    return idx, valid


def block_sparse_attention(q, k, v, layout: np.ndarray, block: int,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           key_padding_mask=None):
    """q/k/v: (B, T, H, D); ``layout``: host numpy (H, T//block, T//block).
    Returns (B, T, H, D)."""
    B, T, H, D = q.shape
    nq = T // block
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    idx_np, valid_np = layout_to_gather_indices(layout)
    M = idx_np.shape[-1]
    idx = jnp.asarray(idx_np)
    valid = jnp.asarray(valid_np)

    # (B, T, H, D) → (B, H, nq, block, D)
    qb = jnp.transpose(q, (0, 2, 1, 3)).reshape(B, H, nq, block, D)
    kb = jnp.transpose(k, (0, 2, 1, 3)).reshape(B, H, nq, block, D)
    vb = jnp.transpose(v, (0, 2, 1, 3)).reshape(B, H, nq, block, D)

    # gather key/value blocks per (h, q-block): (B, H, nq, M, block, D)
    def gather_blocks(x):
        # x: (B, H, nk, block, D); idx: (H, nq, M) → take along axis 2
        return jax.vmap(  # over batch
            lambda xb: jax.vmap(  # over head
                lambda xh, ih: xh[ih], in_axes=(0, 0))(xb, idx))(x)

    kg = gather_blocks(kb)
    vg = gather_blocks(vb)

    s = jnp.einsum("bhqtd,bhqmsd->bhqtms", qb.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale  # (B,H,nq,block,M,block)

    # mask: invalid gather slots; token-level causal inside/over blocks
    mask = jnp.broadcast_to(valid[None, :, :, None, :, None],
                            s.shape)
    if causal:
        q_pos = (jnp.arange(nq)[:, None] * block
                 + jnp.arange(block)[None, :])        # (nq, block)
        k_pos = idx[..., None] * block + jnp.arange(block)  # (H, nq, M, block)
        causal_ok = q_pos[None, :, :, None, None] >= k_pos[:, :, None, :, :]
        mask = mask & causal_ok[None]
    if key_padding_mask is not None:
        # key_padding_mask: (B, T) True=keep → gather to (B,H,nq,M,block)
        kp = key_padding_mask.reshape(B, 1, nq, block)[:, 0]
        kp = jax.vmap(lambda kpb: jax.vmap(
            lambda ih: kpb[ih])(idx))(kp)  # (B, H, nq, M, block)
        mask = mask & kp[:, :, :, None, :, :]

    s = jnp.where(mask, s, NEG_INF)
    flat = s.reshape(B, H, nq, block, M * block)
    # guard fully-masked rows (no live block): softmax over -inf → uniform;
    # kill contributions afterwards
    p = jax.nn.softmax(flat, axis=-1).reshape(s.shape)
    p = jnp.where(mask, p, 0.0)
    o = jnp.einsum("bhqtms,bhqmsd->bhqtd", p, vg.astype(jnp.float32))
    o = o.reshape(B, H, nq * block, D)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)


class SparseSelfAttention:
    """≅ reference ``SparseSelfAttention`` (sparse_self_attention.py:12):
    callable taking (q, k, v) shaped (B, T, H, D) and applying the configured
    block-sparse pattern. Layouts are built once per sequence length and
    cached (static under jit).

    ``kernel``: "auto" routes to the fused Pallas splash-style kernel
    (``pallas_kernel.py``) when the layout granule is MXU-sized
    (block >= 128) and no key-padding mask is given, else the gather
    formulation; "pallas"/"gather" force a path.
    """

    def __init__(self, sparsity_config: SparsityConfig = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 kernel: str = "auto"):
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError(f"key_padding_mask_mode must be add|mul, got "
                             f"{key_padding_mask_mode!r}")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError(f"attn_mask_mode must be add|mul, got "
                             f"{attn_mask_mode!r}")
        if kernel not in ("auto", "pallas", "gather"):
            raise ValueError(f"kernel must be auto|pallas|gather, got "
                             f"{kernel!r}")
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.kernel = kernel
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None,
                 attn_mask=None):
        if attn_mask is not None:
            raise NotImplementedError(
                "dense attn_mask is not supported by the block-sparse kernel "
                "yet; express the pattern via the sparsity config layout")
        cfg = self.sparsity_config
        T = query.shape[1]
        layout = self.get_layout(T)
        causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
        keep = None
        if key_padding_mask is not None:
            # "add": additive float mask (0 keep, large-negative drop);
            # "mul": multiplicative 0/1 mask (reference mask-mode semantics)
            if self.key_padding_mask_mode == "add":
                keep = key_padding_mask > -1.0
            else:
                keep = key_padding_mask > 0

        from .pallas_kernel import block_sparse_flash_attention, supports_pallas
        use_pallas = self.kernel == "pallas" or (
            self.kernel == "auto" and keep is None
            and supports_pallas(cfg.block, T))
        if use_pallas:
            if keep is not None:
                raise NotImplementedError(
                    "key_padding_mask is not supported by the Pallas "
                    "block-sparse kernel; use kernel=\"gather\"")
            return block_sparse_flash_attention(
                query, key, value, layout, cfg.block, causal=causal)
        return block_sparse_attention(
            query, key, value, layout, cfg.block, causal=causal,
            key_padding_mask=keep)


__all__ = [
    "SparseSelfAttention",
    "block_sparse_attention",
    "layout_to_gather_indices",
    "SparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "VariableSparsityConfig",
    "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig",
    "LocalSlidingWindowSparsityConfig",
]
