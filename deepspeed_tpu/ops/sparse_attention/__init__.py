from .sparse_self_attention import (  # noqa: F401
    SparseSelfAttention,
    block_sparse_attention,
    layout_to_gather_indices,
)
from .sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
