"""Block-sparse flash attention as Pallas TPU kernels (splash-style).

The perf-bearing TPU analog of the reference's Triton block-sparse stack —
SDD/DSD block matmuls + sparse softmax (``ops/sparse_attention/matmul.py:17,
628``, ``softmax.py:224``) — fused into flash-attention kernels that iterate
ONLY the live key blocks of a sparsity layout.

Where the dense flash kernel's KV grid dimension walks every key block and
skips masked ones with a predicate, here the KV grid dimension has extent M
(the max live blocks over all (head, q-block) rows) and a scalar-prefetch
index array drives the K/V BlockSpec index maps: grid step m of row (h, i)
DMAs key block ``idx[h, i, m]``. Dead blocks are never fetched — both the
FLOPs and the HBM traffic scale with the layout's density, not O(T²). Rows
with fewer than M live blocks pad ``idx`` by repeating their last live
index: Pallas elides the DMA when consecutive grid steps map to the same
block, and ``m >= cnt[h, i]`` skips the compute, so padding costs only grid
iterations.

Granularity is TPU-native: the sparsity granule is the kernel block
(>=128 — the MXU/lane tile), exactly as the reference's granule is Triton's
16x16 tile. Layouts from any ``SparsityConfig`` with ``block >= 128`` run
here; finer layouts fall back to the gather formulation in
``sparse_self_attention.py`` (exact at any granule, but dense-gather cost).

Backward follows the flash recompute scheme (store per-row lse only) with
the same index-driven fetches: dq re-walks ``idx``; dk/dv walk the
TRANSPOSED layout (``idx_t[h, j]`` = query blocks attending key block j),
so every kernel touches only live tiles.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..attention.flash_attention import LANES, NEG_INF, SUBLANES, _interpret

MIN_KERNEL_BLOCK = 128


def layout_to_schedule(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(H, nq, nk) 0/1 layout → (idx (H, nq, M) int32, cnt (H, nq) int32).

    ``idx[h, i, :cnt[h, i]]`` lists the live key blocks of row (h, i) in
    ascending order; slots past cnt repeat the last live index (DMA-elision
    padding). Rows with no live blocks point at block 0 with cnt 0.
    """
    H, nq, nk = layout.shape
    counts = layout.sum(-1).astype(np.int32)
    M = max(1, int(counts.max()))
    idx = np.zeros((H, nq, M), np.int32)
    for h in range(H):
        for i in range(nq):
            js = np.nonzero(layout[h, i])[0]
            if len(js):
                idx[h, i, :len(js)] = js
                idx[h, i, len(js):] = js[-1]
    return idx, counts


def _sparse_fwd_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                       acc_ref, m_ref, l_ref, *, scale: float, causal: bool,
                       block: int, num_heads: int):
    h = pl.program_id(0) % num_heads
    i = pl.program_id(1)
    m = pl.program_id(2)
    num_m = pl.num_programs(2)

    @pl.when(m == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(m < cnt_ref[h, i])
    def _compute():
        kb = idx_ref[h, i, m]
        # MXU operands stay in the input dtype (bf16 at full rate on v5e);
        # accumulation/statistics fp32; p cast back for the PV dot
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = kb * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(m == num_m - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # rows with no live block keep lse = -inf-ish; exp(s - lse) in the
        # backward is then 0 via the cnt predicate (those rows never run)
        lse_row = (m_ref[:, :1] + jnp.log(l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], lse_ref.shape[1:])


def _sparse_fwd(q, k, v, idx, cnt, *, scale: float, causal: bool, block: int,
                num_heads: int):
    bh, seq, d = q.shape
    nq = seq // block
    M = idx.shape[-1]
    grid = (bh, nq, M)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref: (b, i, 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref:
                         (b, idx_ref[b % num_heads, i, m], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref:
                         (b, idx_ref[b % num_heads, i, m], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref: (b, i, 0)),
            pl.BlockSpec((1, SUBLANES, block),
                         lambda b, i, m, idx_ref, cnt_ref: (b, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, LANES), jnp.float32),
            pltpu.VMEM((block, LANES), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        functools.partial(_sparse_fwd_kernel, scale=scale, causal=causal,
                          block=block, num_heads=num_heads),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, SUBLANES, seq), jnp.float32),
        ],
        interpret=_interpret(),
    )(idx, cnt, q, k, v)
    return out, lse


def _sparse_dq_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_acc_ref, *, scale: float,
                      causal: bool, block: int, num_heads: int):
    h = pl.program_id(0) % num_heads
    i = pl.program_id(1)
    m = pl.program_id(2)
    num_m = pl.num_programs(2)

    @pl.when(m == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    @pl.when(m < cnt_ref[h, i])
    def _compute():
        kb = idx_ref[h, i, m]
        # bf16 MXU operands, fp32 stats/accumulator (see fwd kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = kb * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc_ref[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(m == num_m - 1)
    def _finish():
        dq_ref[0] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)


def _sparse_dkv_kernel(idx_t_ref, cnt_t_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_acc_ref,
                       dv_acc_ref, *, scale: float, causal: bool, block: int,
                       num_heads: int):
    h = pl.program_id(0) % num_heads
    j = pl.program_id(1)
    m = pl.program_id(2)
    num_m = pl.num_programs(2)

    @pl.when(m == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    @pl.when(m < cnt_t_ref[h, j])
    def _compute():
        qb = idx_t_ref[h, j, m]
        # bf16 MXU operands, fp32 stats/accumulators (see fwd kernel note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qb * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = j * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        p_lo = p.astype(do.dtype)
        dv_acc_ref[:] += jax.lax.dot_general(p_lo, do, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(m == num_m - 1)
    def _finish():
        # q is unscaled in the s recompute, so dk picks up the scale here
        dk_ref[0] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _sparse_bwd(q, k, v, out, lse, do, idx, cnt, idx_t, cnt_t, *,
                scale: float, causal: bool, block: int, num_heads: int):
    bh, seq, d = q.shape
    nq = seq // block
    M = idx.shape[-1]
    Mt = idx_t.shape[-1]

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, SUBLANES, seq))

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, M),
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref: (b, i, 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref:
                         (b, idx_ref[b % num_heads, i, m], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref:
                         (b, idx_ref[b % num_heads, i, m], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, i, m, idx_ref, cnt_ref: (b, i, 0)),
            pl.BlockSpec((1, SUBLANES, block),
                         lambda b, i, m, idx_ref, cnt_ref: (b, 0, i)),
            pl.BlockSpec((1, SUBLANES, block),
                         lambda b, i, m, idx_ref, cnt_ref: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block, d),
                               lambda b, i, m, idx_ref, cnt_ref: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_sparse_dq_kernel, scale=scale, causal=causal,
                          block=block, num_heads=num_heads),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        interpret=_interpret(),
    )(idx, cnt, q, k, v, do, lse, delta)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, Mt),
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref:
                         (b, it_ref[b % num_heads, j, m], 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref: (b, j, 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref: (b, j, 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref:
                         (b, it_ref[b % num_heads, j, m], 0)),
            pl.BlockSpec((1, SUBLANES, block),
                         lambda b, j, m, it_ref, ct_ref:
                         (b, 0, it_ref[b % num_heads, j, m])),
            pl.BlockSpec((1, SUBLANES, block),
                         lambda b, j, m, it_ref, ct_ref:
                         (b, 0, it_ref[b % num_heads, j, m])),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref: (b, j, 0)),
            pl.BlockSpec((1, block, d),
                         lambda b, j, m, it_ref, ct_ref: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_sparse_dkv_kernel, scale=scale, causal=causal,
                          block=block, num_heads=num_heads),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        interpret=_interpret(),
    )(idx_t, cnt_t, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.lru_cache(maxsize=32)
def _build_sparse_fn(layout_key, block: int, causal: bool, scale: float,
                     num_heads: int):
    """Construct the custom-VJP attention fn for one (layout, block) pair.

    The schedule arrays are closure constants (the layout is static per
    config + seq length); q/k/v are the only differentiable inputs.
    ``layout_key`` is (bytes, shape) so identical layouts share a cache
    entry across calls.
    """
    layout = np.frombuffer(layout_key[0], np.int32).reshape(layout_key[1])
    # schedule arrays stay HOST numpy in this (lru_cached) closure ON
    # PURPOSE: jnp constants built here would be tracers of whichever
    # trace first populated the cache entry, and a later trace hitting
    # the same key would receive leaked tracers (UnexpectedTracerError).
    # numpy closures materialize fresh per-trace constants on use.
    idx, cnt = layout_to_schedule(layout)
    idx_t, cnt_t = layout_to_schedule(layout.transpose(0, 2, 1))

    @jax.custom_vjp
    def attn(q, k, v):
        out, _ = _sparse_fwd(q, k, v, idx, cnt, scale=scale, causal=causal,
                             block=block, num_heads=num_heads)
        return out

    def attn_fwd(q, k, v):
        from jax.ad_checkpoint import checkpoint_name

        out, lse = _sparse_fwd(q, k, v, idx, cnt, scale=scale, causal=causal,
                               block=block, num_heads=num_heads)
        # same checkpoint_name discipline as flash_attention: lets the
        # "dots" remat policy save (out, lse) and skip re-running the
        # forward kernel in the backward pass
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, (q, k, v, out, lse)

    def attn_bwd(res, do):
        q, k, v, out, lse = res
        return _sparse_bwd(q, k, v, out, lse, do, idx, cnt, idx_t, cnt_t,
                           scale=scale, causal=causal, block=block,
                           num_heads=num_heads)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn


def supports_pallas(layout_block: int, seq_len: int) -> bool:
    """The Pallas path needs MXU-sized sparsity granules and exact tiling."""
    return (layout_block >= MIN_KERNEL_BLOCK
            and layout_block % LANES == 0
            and seq_len % layout_block == 0)


def block_sparse_flash_attention(q, k, v, layout: np.ndarray, block: int,
                                 causal: bool = False,
                                 scale: Optional[float] = None):
    """Fused block-sparse attention. q/k/v: (B, T, H, D); ``layout``: host
    numpy (H, T//block, T//block) 0/1. Returns (B, T, H, D).

    Requires ``supports_pallas(block, T)``; callers route finer layouts to
    the gather formulation.
    """
    B, T, H, D = q.shape
    if not supports_pallas(block, T):
        raise ValueError(
            f"block {block} / seq {T} not supported by the Pallas kernel "
            f"(need block >= {MIN_KERNEL_BLOCK}, block % {LANES} == 0, "
            "T % block == 0)")
    if layout.shape != (H, T // block, T // block):
        raise ValueError(f"layout shape {layout.shape} != "
                         f"{(H, T // block, T // block)}")
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    layout = np.ascontiguousarray(layout.astype(np.int32))
    fn = _build_sparse_fn((layout.tobytes(), layout.shape), block,
                          bool(causal), float(scale), H)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = fn(to_bh(q), to_bh(k), to_bh(v))
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


__all__ = [
    "block_sparse_flash_attention",
    "layout_to_schedule",
    "supports_pallas",
    "MIN_KERNEL_BLOCK",
]
