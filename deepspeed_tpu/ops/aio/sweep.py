"""AIO validation + performance sweep — the reference's
``csrc/aio/py_test/{validate_async_io.py,aio_bench_perf_sweep.py}`` analog.

``validate()`` round-trips data through every (block_size, threads,
o_direct) combination and checks bit-exactness. ``sweep()`` measures
read/write bandwidth per configuration against a scratch file, compares
with the single-threaded synchronous baseline, and returns the results
sorted best-first. CLI::

    python -m deepspeed_tpu.ops.aio.sweep --mb 128 --dir /tmp
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import AioHandle, aio_available, aligned_array, o_direct_supported

DEFAULT_BLOCK_SIZES = (256 * 1024, 1 << 20, 8 << 20)
DEFAULT_THREADS = (1, 2, 4, 8)


def _scratch_file(dir: Optional[str], nbytes: int) -> str:
    fd, path = tempfile.mkstemp(suffix=".aio", dir=dir)
    os.close(fd)
    data = np.random.default_rng(0).integers(
        0, 256, nbytes, dtype=np.uint8)
    data.tofile(path)
    return path


def validate(dir: Optional[str] = None, nbytes: int = 4 << 20) -> bool:
    """Round-trip correctness across the config grid (validate_async_io
    analog). Returns True; raises on any mismatch."""
    path = _scratch_file(dir, nbytes)
    try:
        expect = np.fromfile(path, np.uint8)
        od_options = (False, True) if o_direct_supported(path) else (False,)
        for block in (64 * 1024, 1 << 20):
            for threads in (1, 4):
                for o_direct in od_options:
                    h = AioHandle(num_threads=threads, block_size=block,
                                  queue_depth=32, o_direct=o_direct)
                    buf = aligned_array(nbytes)
                    h.async_pread(buf, path)
                    h.wait()
                    np.testing.assert_array_equal(buf, expect)
                    out_path = path + f".out{block}.{threads}.{o_direct}"
                    h.async_pwrite(buf, out_path)
                    h.wait()
                    np.testing.assert_array_equal(
                        np.fromfile(out_path, np.uint8), expect)
                    os.unlink(out_path)
                    h.close()
        return True
    finally:
        os.unlink(path)


def sync_baseline(path: str, nbytes: int, write: bool = False) -> float:
    """Single-threaded synchronous GB/s (numpy tofile/fromfile)."""
    if write:
        buf = np.random.default_rng(1).integers(0, 256, nbytes,
                                                dtype=np.uint8)
        t0 = time.perf_counter()
        buf.tofile(path)
        with open(path, "rb+") as f:
            os.fsync(f.fileno())
    else:
        t0 = time.perf_counter()
        np.fromfile(path, np.uint8)
    dt = time.perf_counter() - t0
    return nbytes / dt / 1e9


def sweep(file_mb: int = 64, dir: Optional[str] = None,
          block_sizes=DEFAULT_BLOCK_SIZES, threads=DEFAULT_THREADS,
          o_direct_opts=(False,)) -> Dict[str, Any]:
    """Measure read bandwidth per (block_size, threads, o_direct) config.

    Returns {"baseline_gbps", "results": [...best-first...], "best"}.
    """
    nbytes = file_mb << 20
    path = _scratch_file(dir, nbytes)
    results: List[Dict[str, Any]] = []
    try:
        base = sync_baseline(path, nbytes)
        for block in block_sizes:
            for n in threads:
                for od in o_direct_opts:
                    h = AioHandle(num_threads=n, block_size=block,
                                  queue_depth=4 * n, o_direct=od)
                    buf = aligned_array(nbytes)
                    # warmup then timed
                    h.async_pread(buf, path)
                    h.wait()
                    t0 = time.perf_counter()
                    h.async_pread(buf, path)
                    h.wait()
                    dt = time.perf_counter() - t0
                    h.close()
                    results.append({
                        "block_size": block, "threads": n, "o_direct": od,
                        # honest flag: False when the fs rejects O_DIRECT
                        # and chunks actually went through the page cache
                        "o_direct_effective": od and o_direct_supported(path),
                        "read_gbps": nbytes / dt / 1e9,
                        "speedup_vs_sync": (nbytes / dt / 1e9) / max(base, 1e-9),
                    })
        results.sort(key=lambda r: -r["read_gbps"])
        return {"baseline_gbps": base, "results": results,
                "best": results[0]}
    finally:
        os.unlink(path)


def main() -> None:
    ap = argparse.ArgumentParser(description="AIO perf sweep")
    ap.add_argument("--mb", type=int, default=64)
    ap.add_argument("--dir", default=None)
    ap.add_argument("--o_direct", action="store_true")
    args = ap.parse_args()
    if not aio_available():
        raise SystemExit("aio library not available on this host")
    validate(dir=args.dir)
    out = sweep(file_mb=args.mb, dir=args.dir,
                o_direct_opts=(False, True) if args.o_direct else (False,))
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
