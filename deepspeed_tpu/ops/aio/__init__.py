"""Async file I/O handle (≅ reference ``csrc/aio/py_lib/deepspeed_py_aio_
handle.cpp`` API: async_pread/async_pwrite/wait), ctypes-bound.

Used by the NVMe offload tier (``runtime/zero/offload.py``) to swap
optimizer-state / parameter buffers against local SSD with overlapped I/O.
Configuration mirrors the reference's ``aio`` JSON block: ``block_size``
(chunking granularity — every request fans out into block-size chunks
across the thread pool), ``queue_depth`` (max queued chunks; backpressure),
``thread_count``, and O_DIRECT routing for aligned chunks.
``single_submit``/``overlap_events`` are accepted for config parity but are
no-ops in the thread-pool model (chunk submission is always overlapped).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..op_builder import AsyncIOBuilder


def aligned_array(nbytes: int, dtype=np.uint8, align: int = 4096) -> np.ndarray:
    """A numpy buffer whose data pointer is ``align``-aligned — required for
    chunks to take the O_DIRECT path (the reference's pinned aligned
    tensors, csrc/aio/py_lib/deepspeed_pin_tensor.cpp)."""
    itemsize = np.dtype(dtype).itemsize
    n = (nbytes + itemsize - 1) // itemsize
    raw = np.empty(n * itemsize + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + n * itemsize].view(dtype)


class AioHandle:
    """Thread-pool async file I/O. numpy-array in/out, byte offsets.

    Args mirror the reference handle (aio_bench vocabulary): block_size,
    queue_depth, thread_count, single_submit, overlap_events, o_direct.
    """

    def __init__(self, num_threads: int = 4, block_size: int = 1 << 20,
                 queue_depth: int = 0, o_direct: bool = False,
                 single_submit: bool = False, overlap_events: bool = True):
        del single_submit, overlap_events  # parity-only (see module doc)
        if block_size < 4096:
            raise ValueError(
                f"block_size must be >= 4096 bytes, got {block_size} (the "
                f"chunking granularity; O_DIRECT alignment unit)")
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.ds_aio_create(num_threads, block_size,
                                          queue_depth, int(o_direct))
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.num_threads = num_threads
        self.o_direct = o_direct
        # submitted buffers stay alive until their ticket completes (or a
        # full wait()): keyed per ticket so long-running per-ticket users
        # (the layer-streamed finalize) do not accumulate O(model) refs
        self._refs = {}

    def async_pwrite(self, array: np.ndarray, path: str,
                     offset: int = 0) -> int:
        """Submit; returns a completion ticket for ``wait_ticket``."""
        a = np.ascontiguousarray(array)
        t = self._lib.ds_aio_pwrite(self._h, os.fsencode(path),
                                    a.ctypes.data, a.nbytes, offset)
        self._refs[t] = a
        return t

    def async_pread(self, array: np.ndarray, path: str,
                    offset: int = 0) -> int:
        """Submit; returns a completion ticket for ``wait_ticket``."""
        assert array.flags["C_CONTIGUOUS"] and array.flags["WRITEABLE"]
        t = self._lib.ds_aio_pread(self._h, os.fsencode(path),
                                   array.ctypes.data, array.nbytes, offset)
        self._refs[t] = array
        return t

    # reference-named blocking variants (deepspeed_py_aio_handle's sync_*
    # calls return only after the I/O completes)
    def sync_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pwrite(array, path, offset)
        self.wait()

    def sync_pread(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pread(array, path, offset)
        self.wait()

    def wait(self) -> int:
        """Blocks until all pending requests finish; returns the number of
        FAILED chunks (0 = success), raising on failure."""
        errors = self._lib.ds_aio_wait(self._h)
        self._refs.clear()
        if errors:
            raise IOError(f"aio: {errors} chunk(s) failed")
        return 0

    def wait_ticket(self, ticket: int) -> None:
        """Blocks until ONE submitted request completes (the pipelined
        swap-in path: wait for a leaf's read while later leaves keep
        streaming); releases that ticket's buffer reference."""
        errors = self._lib.ds_aio_wait_ticket(self._h, ticket)
        self._refs.pop(ticket, None)
        if errors:
            raise IOError(f"aio: {errors} chunk(s) failed (ticket {ticket})")

    def pending(self) -> int:
        return self._lib.ds_aio_pending(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._lib.ds_aio_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def o_direct_supported(path: str) -> bool:
    """True when the filesystem holding ``path`` accepts O_DIRECT opens —
    tmpfs and some network filesystems do not, in which case the handle
    silently serves every chunk from the buffered fd."""
    lib = AsyncIOBuilder().load()
    return bool(lib.ds_aio_probe_o_direct(os.fsencode(path)))


def aio_available() -> bool:
    return AsyncIOBuilder().is_compatible()
