"""Async file I/O handle (≅ reference ``csrc/aio/py_lib/deepspeed_py_aio_
handle.cpp`` API: async_pread/async_pwrite/wait), ctypes-bound.

Used by the NVMe offload tier (``runtime/zero/offload.py``) to swap
optimizer-state / parameter buffers against local SSD with overlapped I/O.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..op_builder import AsyncIOBuilder


class AioHandle:
    """Thread-pool async file I/O. numpy-array in/out, byte offsets."""

    def __init__(self, num_threads: int = 4):
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.ds_aio_create(num_threads)
        self._refs = []  # keep submitted buffers alive until wait()

    def async_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        a = np.ascontiguousarray(array)
        self._refs.append(a)
        self._lib.ds_aio_pwrite(self._h, os.fsencode(path),
                                a.ctypes.data, a.nbytes, offset)

    def async_pread(self, array: np.ndarray, path: str, offset: int = 0) -> None:
        assert array.flags["C_CONTIGUOUS"] and array.flags["WRITEABLE"]
        self._refs.append(array)
        self._lib.ds_aio_pread(self._h, os.fsencode(path),
                               array.ctypes.data, array.nbytes, offset)

    def wait(self) -> int:
        """Blocks until all pending requests finish; returns the number of
        FAILED requests (0 = success), raising on failure."""
        errors = self._lib.ds_aio_wait(self._h)
        self._refs.clear()
        if errors:
            raise IOError(f"aio: {errors} request(s) failed")
        return 0

    def pending(self) -> int:
        return self._lib.ds_aio_pending(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._lib.ds_aio_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def aio_available() -> bool:
    return AsyncIOBuilder().is_compatible()
