"""Fused optimizers.

TPU-native equivalents of the reference's native optimizer kernels:
``FusedAdam`` (csrc/adam/multi_tensor_adam.cu, ops/adam/fused_adam.py:18),
``FusedLamb`` (csrc/lamb/fused_lamb_cuda_kernel.cu, ops/lamb/fused_lamb.py:14),
and ``DeepSpeedCPUAdam`` math (csrc/adam/cpu_adam.cpp — the host-offloaded
variant lives in the offload tier and shares this update rule).

"Fused multi-tensor" on TPU means: the whole-pytree update is one XLA program
— the compiler fuses the elementwise chain across all parameters, which is
what multi_tensor_apply hand-builds on CUDA. State and updates are pure
functions of (grads, state, params) so they run sharded under GSPMD: with
ZeRO, master params / moments are sharded over the data axis and each chip
updates only its shard.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class OptimizerDef(NamedTuple):
    """A functional optimizer: aligned-pytree state, pure update."""

    init: Callable[[Any], Any]  # master_params -> opt_state
    update: Callable[..., Tuple[Any, Any]]  # (grads, state, params, lr, step) -> (new_p, new_s)
    name: str


class AdamState(NamedTuple):
    exp_avg: Any  # first moment, aligned with params
    exp_avg_sq: Any  # second moment



def _multi_map(fn, n_out: int, *trees):
    """tree_map a function returning an n-tuple; transpose into n trees.

    Safe against tuples appearing inside the input pytrees (unlike
    is_leaf=isinstance-tuple extraction)."""
    outs = jax.tree_util.tree_map(fn, *trees)
    treedef = jax.tree_util.tree_structure(trees[0])
    flat = treedef.flatten_up_to(outs)
    return tuple(jax.tree_util.tree_unflatten(treedef, [f[i] for f in flat])
                 for i in range(n_out))

def _tree_zeros_like(tree, dtype=jnp.float32):
    return jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, dtype), tree)


def fused_adam(betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0,
               adam_w_mode: bool = True, bias_correction: bool = True) -> OptimizerDef:
    """Adam/AdamW (≅ FusedAdam, reference ops/adam/fused_adam.py:18).

    ``adam_w_mode=True`` → decoupled weight decay (AdamW); False → L2-style
    decay added to the gradient, matching the reference's flag.
    """
    beta1, beta2 = betas

    def init(params):
        return AdamState(exp_avg=_tree_zeros_like(params), exp_avg_sq=_tree_zeros_like(params))

    def update(grads, state: AdamState, params, lr, step):
        # step is 1-indexed at the time of the update
        t = step.astype(jnp.float32) + 1.0
        if bias_correction:
            bc1 = 1.0 - beta1 ** t
            bc2 = 1.0 - beta2 ** t
        else:
            bc1 = bc2 = 1.0

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0 and not adam_w_mode:
                g = g + weight_decay * p32
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * (g * g)
            denom = jnp.sqrt(v / bc2) + eps
            new_p = p32 - lr * (m / bc1) / denom
            if weight_decay != 0.0 and adam_w_mode:
                new_p = new_p - lr * weight_decay * p32
            return new_p.astype(p.dtype), m, v

        new_p, new_m, new_v = _multi_map(upd, 3, params, grads, state.exp_avg, state.exp_avg_sq)
        return new_p, AdamState(exp_avg=new_m, exp_avg_sq=new_v)

    return OptimizerDef(init=init, update=update, name="FusedAdam")


def fused_lamb(betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0,
               max_coeff: float = 10.0, min_coeff: float = 0.01,
               bias_correction: bool = True) -> OptimizerDef:
    """LAMB with per-parameter trust ratio (≅ FusedLamb,
    reference ops/lamb/fused_lamb.py:14; trust-ratio clamp max_coeff/min_coeff)."""
    beta1, beta2 = betas

    def init(params):
        return AdamState(exp_avg=_tree_zeros_like(params), exp_avg_sq=_tree_zeros_like(params))

    def update(grads, state: AdamState, params, lr, step):
        t = step.astype(jnp.float32) + 1.0
        bc1 = 1.0 - beta1 ** t if bias_correction else 1.0
        bc2 = 1.0 - beta2 ** t if bias_correction else 1.0

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * (g * g)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p32
            # layer-wise trust ratio; psum over the data axis is implicit —
            # under GSPMD the norms of sharded tensors are computed globally
            p_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(u)
            trust = jnp.where((p_norm > 0) & (u_norm > 0),
                              jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)
            new_p = p32 - lr * trust * u
            return new_p.astype(p.dtype), m, v

        new_p, new_m, new_v = _multi_map(upd, 3, params, grads, state.exp_avg, state.exp_avg_sq)
        return new_p, AdamState(exp_avg=new_m, exp_avg_sq=new_v)

    return OptimizerDef(init=init, update=update, name="FusedLamb")


class SGDState(NamedTuple):
    momentum_buf: Any


def sgd(momentum: float = 0.0, weight_decay: float = 0.0, nesterov: bool = False) -> OptimizerDef:
    def init(params):
        return SGDState(momentum_buf=_tree_zeros_like(params))

    def update(grads, state: SGDState, params, lr, step):
        del step

        def upd(p, g, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p32
            buf = momentum * buf + g
            d = g + momentum * buf if nesterov else buf
            return (p32 - lr * d).astype(p.dtype), buf

        new_p, new_b = _multi_map(upd, 2, params, grads, state.momentum_buf)
        return new_p, SGDState(momentum_buf=new_b)

    return OptimizerDef(init=init, update=update, name="SGD")


def adagrad(eps: float = 1e-8, weight_decay: float = 0.0) -> OptimizerDef:
    """≅ DeepSpeedCPUAdagrad math (csrc/adagrad/cpu_adagrad.cpp)."""

    class AdagradState(NamedTuple):
        accum: Any

    def init(params):
        return AdagradState(accum=_tree_zeros_like(params))

    def update(grads, state, params, lr, step):
        del step

        def upd(p, g, acc):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p32
            acc = acc + g * g
            return (p32 - lr * g / (jnp.sqrt(acc) + eps)).astype(p.dtype), acc

        new_p, new_a = _multi_map(upd, 2, params, grads, state.accum)
        return new_p, AdagradState(accum=new_a)

    return OptimizerDef(init=init, update=update, name="Adagrad")


# --- registry keyed by the reference's optimizer names --------------------
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"


def _adam_factory(params: Dict) -> OptimizerDef:
    return fused_adam(
        betas=tuple(params.get("betas", (0.9, 0.999))),
        eps=params.get("eps", 1e-8),
        weight_decay=params.get("weight_decay", 0.0),
        adam_w_mode=params.get("adam_w_mode", True),
        bias_correction=params.get("bias_correction", True),
    )


def _adamw_factory(params: Dict) -> OptimizerDef:
    p = dict(params)
    p["adam_w_mode"] = True
    return _adam_factory(p)


def _lamb_factory(params: Dict) -> OptimizerDef:
    return fused_lamb(
        betas=tuple(params.get("betas", (0.9, 0.999))),
        eps=params.get("eps", 1e-8),
        weight_decay=params.get("weight_decay", 0.0),
        max_coeff=params.get("max_coeff", 10.0),
        min_coeff=params.get("min_coeff", 0.01),
    )


def _sgd_factory(params: Dict) -> OptimizerDef:
    return sgd(momentum=params.get("momentum", 0.0),
               weight_decay=params.get("weight_decay", 0.0),
               nesterov=params.get("nesterov", False))


def _adagrad_factory(params: Dict) -> OptimizerDef:
    return adagrad(eps=params.get("eps", 1e-8), weight_decay=params.get("weight_decay", 0.0))


def _onebit_adam_factory(params: Dict) -> OptimizerDef:
    from ..runtime.fp16.onebit.adam import onebit_adam

    return onebit_adam(
        betas=tuple(params.get("betas", (0.9, 0.999))),
        eps=params.get("eps", 1e-8),
        weight_decay=params.get("weight_decay", 0.0),
        freeze_step=params.get("freeze_step", 100000),
        adam_w_mode=params.get("adam_w_mode", True),
        bias_correction=params.get("bias_correction", True))


def _onebit_lamb_factory(params: Dict) -> OptimizerDef:
    from ..runtime.fp16.onebit.lamb import onebit_lamb

    return onebit_lamb(
        betas=tuple(params.get("betas", (0.9, 0.999))),
        eps=params.get("eps", 1e-8),
        weight_decay=params.get("weight_decay", 0.0),
        freeze_step=params.get("freeze_step", 100000),
        max_coeff=params.get("max_coeff", 10.0),
        min_coeff=params.get("min_coeff", 0.01),
        coeff_beta=params.get("coeff_beta", 0.9))


def _zero_one_adam_factory(params: Dict) -> OptimizerDef:
    from ..runtime.fp16.onebit.zoadam import zero_one_adam

    return zero_one_adam(
        betas=tuple(params.get("betas", (0.9, 0.999))),
        eps=params.get("eps", 1e-8),
        weight_decay=params.get("weight_decay", 0.0),
        var_freeze_step=params.get("var_freeze_step", 100000),
        var_update_scaler=params.get("var_update_scaler", 16),
        local_step_scaler=params.get("local_step_scaler", 32678),
        local_step_clipper=params.get("local_step_clipper", 16))


OPTIMIZER_REGISTRY: Dict[str, Callable[[Dict], OptimizerDef]] = {
    ADAM_OPTIMIZER: _adam_factory,
    ADAMW_OPTIMIZER: _adamw_factory,
    LAMB_OPTIMIZER: _lamb_factory,
    SGD_OPTIMIZER: _sgd_factory,
    ADAGRAD_OPTIMIZER: _adagrad_factory,
    ONEBIT_ADAM_OPTIMIZER: _onebit_adam_factory,
    ONEBIT_LAMB_OPTIMIZER: _onebit_lamb_factory,
    ZERO_ONE_ADAM_OPTIMIZER: _zero_one_adam_factory,
}


def get_optimizer(type_name: Optional[str], params: Optional[Dict] = None) -> OptimizerDef:
    """Build an optimizer from the config's ``optimizer.type`` (reference
    engine._configure_basic_optimizer, engine.py:1205 name dispatch)."""
    name = (type_name or "adam").lower()
    params = dict(params or {})
    params.pop("lr", None)  # lr flows through the schedule, not the def
    if name in OPTIMIZER_REGISTRY:
        return OPTIMIZER_REGISTRY[name](params)
    raise ValueError(f"Unknown optimizer type {type_name!r}; "
                     f"supported: {sorted(OPTIMIZER_REGISTRY)}")
