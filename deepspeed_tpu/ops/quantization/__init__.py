"""Int8 serving compute: Pallas dequant-GEMM + the int8-at-rest Dense.

TPU analog of the reference's ``csrc/quantization`` inference kernels.
"""

from .int8_matmul import (  # noqa: F401
    int8_matmul,
    int8_matmul_reference,
    quantize_columns,
)
from .linear import QuantDense, pad_features  # noqa: F401
