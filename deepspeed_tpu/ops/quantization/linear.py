"""Int8-at-rest linear layer for serving.

``QuantDense`` is the drop-in serving replacement for ``nn.Dense`` behind
the inference engine's weight-quantization tier (reference
``weight_quantizer.py`` + the fused dequant-GEMM in
``csrc/transformer/inference/csrc/dequantize.cu``): parameters are an
int8 ``kernel`` plus f32 per-output-channel ``scale``, and the forward is
the Pallas :func:`int8_matmul` so weights stream from HBM as int8.

Feature counts are padded up to a lane multiple (128) at parameter-build
time so every kernel call tiles; the pad columns carry zero weights and
the output is sliced back to ``features``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .int8_matmul import int8_matmul, int8_matmul_reference

LANE = 128


def pad_features(features: int) -> int:
    """Feature count padded to the vector-lane multiple QuantDense stores."""
    return -(-features // LANE) * LANE


class QuantDense(nn.Module):
    """Dense layer with int8 kernel + per-output-channel f32 scale.

    ``kernel_mode``: ``auto`` uses the Pallas kernel on TPU and the jnp
    reference elsewhere; ``on`` forces the kernel (interpret mode
    off-TPU — for tests); ``off`` forces the jnp reference. Compute runs
    in bf16 regardless of ``dtype`` (the quantized tier's compute
    contract); ``dtype`` is the output dtype.
    """

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_mode: str = "auto"

    @nn.compact
    def __call__(self, x):
        K = x.shape[-1]
        n_pad = pad_features(self.features)
        kernel = self.param("kernel", nn.initializers.zeros, (K, n_pad),
                            jnp.int8)
        scale = self.param("scale", nn.initializers.ones, (1, n_pad),
                           jnp.float32)
        if self.kernel_mode == "off":
            y = int8_matmul_reference(x, kernel, scale, out_dtype=self.dtype)
        else:
            y = int8_matmul(x, kernel, scale, out_dtype=self.dtype,
                            interpret=(True if self.kernel_mode == "on" and
                                       jax.default_backend() != "tpu"
                                       else None))
        if n_pad != self.features:
            y = y[..., :self.features]
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.dtype)
            y = y + bias
        return y
