"""Int8-weight matmul with in-kernel dequantization (Pallas TPU kernel).

TPU-native serving analog of the reference's int8 inference tier — the
fused dequant-GEMM path (``csrc/quantization/quantize.cu`` +
``csrc/transformer/inference/csrc/dequantize.cu``), where weights live in
HBM as int8 + per-channel scales and are expanded to compute precision
inside the GEMM rather than materialized.

Decode-time matmuls are HBM-bandwidth bound: activations are a few rows,
weights are the traffic. Keeping kernels int8 at rest halves the bytes the
matmul streams per step versus bf16 — the int8 tile is converted to bf16
on the VMEM-resident copy right before the MXU contraction, so
full-precision weights never touch HBM. An XLA-only formulation can fuse
the convert too, but hoists the dequant out of ``lax.scan`` decode loops
(materializing a bf16 copy); the Pallas kernel makes the fusion
structural.

Quantization is per-OUTPUT-channel (scale per column of W): the scale
multiply then applies to the f32 accumulator at flush time — one VPU
convert per weight element instead of a convert+scale+round-trip through
f32 — which is what makes the kernel beat the bf16 matmul instead of
merely matching it (measured 1.15-2.2x at decode shapes,
benchmarks/int8_bench_results.json).

Layout: x (..., K) float, w int8 (K, N), scales f32 (1, N) or (N,).
K on sublanes, N on lanes; blocks over K and N must be 128-multiples (or
the full dimension) — `int8_matmul` falls back to the jnp reference
formulation for shapes that can't tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_K = 1024

# VMEM the kernel may claim: ~16 MB/core on current TPUs; leave headroom
# for Mosaic's own staging. Shapes whose tile plan exceeds this run the
# jnp reference instead of failing to compile at serve time.
VMEM_BUDGET_BYTES = 10 * 1024 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _plan_vmem_bytes(bm: int, bk: int, bn: int) -> int:
    """Worst-case VMEM for one grid step: double-buffered inputs (x bf16;
    w int8 plus its in-kernel bf16 expansion; scale row), f32 accumulator
    scratch and the output tile."""
    inputs = bm * bk * 2 + bk * bn * (1 + 2) + bn * 4
    return 2 * inputs + bm * bn * (4 + 2)


def kernel_plan(M: int, K: int, N: int, block_m: int = DEFAULT_BLOCK_M,
                block_n: int = DEFAULT_BLOCK_N,
                block_k: int = DEFAULT_BLOCK_K,
                vmem_budget: Optional[int] = VMEM_BUDGET_BYTES):
    """Tile plan (bm, bk, bn) for the Pallas kernel, or ``None`` when the
    shape should take the jnp reference: untileable K/N, or a plan (e.g.
    the full-dimension fallback for non-128-multiple dims) whose operand
    tiles would blow the VMEM budget. ``vmem_budget=None`` skips the
    budget gate (interpret mode has no VMEM)."""
    bk = _pick_block(block_k, K)
    bn = _pick_block(block_n, N)
    if bk == 0 or bn == 0:
        return None
    bm = min(block_m, max(8, -(-M // 8) * 8))
    if vmem_budget is not None and \
            _plan_vmem_bytes(bm, bk, bn) > vmem_budget:
        return None
    return bm, bk, bn


def _pick_block(limit: int, n: int, full_cap: int = 4096) -> int:
    """Mosaic block rule for a lane dimension: the block must be a
    128-multiple that divides ``n``, or the full dimension. Returns the
    largest valid choice <= limit (falling back to the full dim when it
    fits in ``full_cap``), else 0 — caller takes the jnp path."""
    best = 0
    d = 128
    while d <= min(limit, n):
        if n % d == 0:
            best = d
        d += 128
    if best == 0 and n <= full_cap:
        best = n
    return best


def quantize_columns(w, num_bits: int = 8):
    """Per-output-channel symmetric quantization: int8 values + f32 scale
    per column. numpy/jnp polymorphic; the serving-side companion of
    ``WeightQuantization`` (reference weight_quantizer.py) shaped for this
    kernel's layout."""
    import numpy as np

    v = np.asarray(w, np.float32)
    q_range = 2 ** (num_bits - 1) - 1
    scales = np.abs(v).max(axis=0, keepdims=True) / q_range    # (1, N)
    scales = np.where(scales == 0, 1.0, scales).astype(np.float32)
    q = np.clip(np.round(v / scales), -q_range - 1, q_range).astype(np.int8)
    return q, scales


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref):
    k = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.bfloat16), w_ref[...].astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == num_k - 1)
    def _flush():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def int8_matmul_reference(x: jnp.ndarray, w: jnp.ndarray, scales: jnp.ndarray,
                          out_dtype=jnp.bfloat16) -> jnp.ndarray:
    """jnp formulation (dequant then dot) — numerics oracle and the
    fallback for shapes the kernel can't tile / non-TPU backends."""
    y = jax.lax.dot_general(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                            (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return (y * scales.reshape(1, -1)).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_m", "block_n",
                                             "block_k", "interpret"))
def _int8_matmul_2d(x, w, scales, *, out_dtype, block_m, block_n, block_k,
                    interpret):
    M, K = x.shape
    N = w.shape[1]
    bm = min(block_m, max(8, -(-M // 8) * 8))
    m_pad = -(-M // bm) * bm
    if m_pad != M:
        x = jnp.pad(x, ((0, m_pad - M), (0, 0)))
    grid = (m_pad // bm, N // block_n, K // block_k)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, block_n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, w, scales.reshape(1, N).astype(jnp.float32))
    return out[:M]


def int8_matmul(x: jnp.ndarray, w: jnp.ndarray, scales: jnp.ndarray,
                out_dtype=jnp.bfloat16,
                block_m: int = DEFAULT_BLOCK_M,
                block_n: int = DEFAULT_BLOCK_N,
                block_k: int = DEFAULT_BLOCK_K,
                interpret: Optional[bool] = None) -> jnp.ndarray:
    """``(x @ w_int8) * scales`` with the int8 expansion fused in-kernel.

    x: (..., K) floating; w: (K, N) int8; scales: (N,) or (1, N) f32
    per-output-channel. Returns (..., N) in ``out_dtype``. Shapes whose
    K/N can't satisfy the tiling rules (or whose plan exceeds the VMEM
    budget) run the jnp reference instead. Off-TPU the reference runs
    unless the caller forces the kernel with ``interpret=True``
    (kernel_mode='on' test forcing) — interpret-mode Pallas is orders of
    magnitude slower than the jnp formulation.
    """
    forced = interpret is True
    if interpret is None:
        if _interpret():
            return int8_matmul_reference(x, w, scales, out_dtype)
        interpret = False
    K, N = w.shape
    batch_shape = x.shape[:-1]
    x2 = x.reshape(-1, K)
    # forced interpret mode has no VMEM: only untileable K/N bail there
    plan = kernel_plan(x2.shape[0], K, N, block_m, block_n, block_k,
                       vmem_budget=None if forced else VMEM_BUDGET_BYTES)
    if plan is None:
        return int8_matmul_reference(x, w, scales, out_dtype)
    _, bk, bn = plan
    y = _int8_matmul_2d(x2, w, scales, out_dtype=jnp.dtype(out_dtype),
                        block_m=block_m, block_n=bn, block_k=bk,
                        interpret=interpret)
    return y.reshape(*batch_shape, N)
