"""Param-tree conversion to the int8 serving layout.

Shared by the inference engine's int8 compute tier and int8
ZeRO-Inference streaming: every Dense kernel in a TransformerLM param
tree becomes {kernel: int8, scale: f32 per-output-channel} consumed by
:class:`QuantDense` (reference analog: ``weight_quantizer.py`` +
``csrc/transformer/inference/csrc/dequantize.cu``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .int8_matmul import quantize_columns
from .linear import pad_features

DENSE_KEYS = frozenset({
    "q_proj", "k_proj", "v_proj", "o_proj",
    "up_proj", "gate_proj", "down_proj", "lm_head"})


def _quantize_one(kern2d):
    """One (K, N) kernel -> padded int8 + f32 column scales. Materializes
    only this kernel in f32 (memmap-friendly: a stacked (L, K, N) leaf is
    processed one layer slice at a time by the caller)."""
    kern2d = np.asarray(kern2d, np.float32)
    n = kern2d.shape[-1]
    n_pad = pad_features(n)
    if n_pad != n:
        kern2d = np.pad(kern2d, ((0, 0), (0, n_pad - n)))
    return quantize_columns(kern2d)


def _quantize_kernel(kern):
    # NOTE: outputs are host numpy ON PURPOSE — callers that stream
    # (ZeroInferenceEngine) must not have the quantized model committed
    # to device memory; the resident engine device_puts the tree itself.
    if np.ndim(kern) == 2:
        return _quantize_one(kern)
    qs = [_quantize_one(layer) for layer in kern]  # nn.scan-stacked
    return (np.stack([a for a, _ in qs]),
            np.stack([b for _, b in qs]))


def quantize_lm_params(params, dense_keys=DENSE_KEYS) -> Tuple[dict, int]:
    """bf16/f32 TransformerLM param tree -> QuantDense tree (host numpy).
    Returns (quantized tree, number of Dense kernels converted). Memmap
    inputs are read one layer slice at a time; the OUTPUT int8 tree is
    materialized in host RAM (~0.5x the bf16 checkpoint bytes)."""
    import flax

    n_dense = 0

    def walk(tree):
        nonlocal n_dense
        out = {}
        for key, val in tree.items():
            if not isinstance(val, (dict, type(None))) and \
                    hasattr(val, "items"):
                val = dict(val)
            if key in dense_keys and isinstance(val, dict) \
                    and "kernel" in val and np.ndim(val["kernel"]) >= 2:
                q, s = _quantize_kernel(val["kernel"])
                new = {"kernel": q, "scale": s}
                if "bias" in val:
                    new["bias"] = val["bias"]
                out[key] = new
                n_dense += 1
            elif isinstance(val, dict):
                out[key] = walk(val)
            else:
                out[key] = val
        return out

    return walk(flax.core.unfreeze(params)), n_dense
