"""Random-LTD token selection / gather / scatter.

TPU-native equivalent of the reference random-LTD kernels
(``csrc/random_ltd/{token_sort.cu,gather_scatter.cu}``, bound in
``ops/random_ltd/dropping_utils.py:82,106``): select a random *sorted*
subset of token positions per sequence, gather them for the wrapped layer,
and scatter the layer's outputs back over the originals. On TPU these are
pure ``jnp`` gathers (XLA lowers them to efficient dynamic-slices); sorting
keeps relative token order, matching the reference's token_sort kernel.

All shapes are static under jit: ``reserved_length`` must be a Python int
at trace time (the scheduler buckets it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(rng: jax.Array, batch: int, seq_length: int,
                  reserved_length: int) -> jnp.ndarray:
    """Per-sequence sorted random selection of ``reserved_length`` positions
    out of ``seq_length`` — reference gpt_sample_tokens/bert_sample_tokens.
    Returns int32 indices of shape (batch, reserved_length)."""
    if reserved_length >= seq_length:
        return jnp.broadcast_to(jnp.arange(seq_length, dtype=jnp.int32),
                                (batch, seq_length))
    noise = jax.random.uniform(rng, (batch, seq_length))
    # indices of the reserved_length smallest noise values, then sort to
    # preserve token order (token_sort.cu)
    _, idx = jax.lax.top_k(-noise, reserved_length)
    return jnp.sort(idx, axis=-1).astype(jnp.int32)


def gather_tokens(x: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Gather (batch, seq, hidden) → (batch, reserved, hidden) —
    reference gather_scatter.cu forward."""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def scatter_tokens(base: jnp.ndarray, updated: jnp.ndarray,
                   indices: jnp.ndarray) -> jnp.ndarray:
    """Scatter (batch, reserved, hidden) back into (batch, seq, hidden);
    unselected positions keep ``base`` — reference gather_scatter.cu
    backward path / vanilla-scatter."""
    batch_idx = jnp.arange(base.shape[0])[:, None]
    return base.at[batch_idx, indices].set(updated)
