from .dropping_utils import gather_tokens, sample_tokens, scatter_tokens

__all__ = ["sample_tokens", "gather_tokens", "scatter_tokens"]
