"""Inference config (≅ reference ``deepspeed/inference/config.py:126
DeepSpeedInferenceConfig``): same JSON surface, pydantic-typed.

Keys the reference exposes that are CUDA-machinery (``enable_cuda_graph``,
``use_triton``) are accepted for config compatibility and ignored — their
TPU equivalents (whole-graph jit compile) are always on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """≅ reference inference/config.py DeepSpeedTPConfig."""

    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


class InferenceCheckpointConfig(DeepSpeedConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    kernel_inject: bool = Field(False, alias="replace_with_kernel_inject")
    dtype: str = "bfloat16"  # float32 | float16 | bfloat16 | int8
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    enable_cuda_graph: bool = False      # accepted, no-op on TPU
    use_triton: bool = False             # accepted, no-op on TPU
    triton_autotune: bool = False        # accepted, no-op on TPU
    zero: Dict = Field(default_factory=dict)
    checkpoint: Union[str, Dict, None] = None
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = 1
    max_batch_size: Optional[int] = None
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = None
    return_tuple: bool = True
    # sampling defaults for generate()
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0

    @property
    def mp_size(self) -> int:
        return self.tensor_parallel.tp_size

    def jnp_dtype(self):
        import jax.numpy as jnp

        from ..utils.logging import logger

        table = {"float32": jnp.float32, "fp32": jnp.float32,
                 "float16": jnp.float16, "fp16": jnp.float16,
                 "half": jnp.float16,
                 "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                 "int8": jnp.bfloat16}
        key = str(self.dtype).replace("torch.", "")
        if key not in table:
            raise ValueError(f"unsupported inference dtype {self.dtype!r}; "
                             f"supported: {sorted(table)}")
        if key == "int8":
            logger.info(
                "dtype=int8: weights stored int8; single-device LM serving "
                "computes via the Pallas dequant-GEMM (activations bf16), "
                "TP>1 and non-LM modules dequantize in-jit")
        return table[key]
