"""Inference engine (≅ reference ``deepspeed/inference/engine.py:89
InferenceEngine``), TPU-first.

The reference's pipeline — policy/container kernel injection, TP weight
slicing (``engine.py:259,314``), CUDA-graph capture (``:532,551``), KV-cache
workspace (inference_context.h) — maps to:

* injection → :func:`module_inject.replace_module` produces sharding rules;
  TP slicing is a ``NamedSharding`` placement, XLA inserts the allreduces;
* CUDA graphs → whole-step ``jax.jit`` (always on; ``enable_cuda_graph``
  accepted and ignored);
* KV cache → the model's flax ``cache`` collection, statically shaped at
  the model's ``max_seq_len``, donated through the decode step so updates
  are in-place in HBM (``max_out_tokens`` is accepted for config
  compatibility; capacity is the model's, and generate() enforces it).

``generate()`` runs a jitted prefill then a jitted single-token decode loop
with greedy/temperature/top-k/top-p sampling.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .. import comm as dist
from ..module_inject import replace_module
from ..parallel import mesh as mesh_mod
from ..parallel.axis_rules import physical_spec
from ..runtime.zero.policy import ShardingRules, _path_str
from ..utils.logging import log_dist
from .config import DeepSpeedInferenceConfig


def _filter_logits(last, temperature, top_k, top_p):
    """Temperature/top-k/top-p filtering on raw fp32 logits (masked-out
    entries at -1e30). Shared between the sampling path and speculative
    verification (serving/spec_decode) — acceptance probabilities must be
    computed under EXACTLY the distribution the sampler draws from.
    ``last`` is (..., V); temperature traced, top_k/top_p static."""
    V = last.shape[-1]
    scaled = last / jnp.maximum(temperature, 1e-6)
    top_k = min(top_k, V)
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    if top_p < 1.0:
        sorted_ = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest prefix with mass >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_, cutoff_idx[..., None], axis=-1)
        scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    return scaled


class InferenceEngine:
    """Construct via :func:`deepspeed_tpu.init_inference`."""

    def __init__(self, model: Any = None,
                 config: Union[str, Dict, DeepSpeedInferenceConfig, None] = None,
                 model_parameters: Any = None, mesh=None, **kwargs):
        dist.init_distributed()
        if isinstance(config, DeepSpeedInferenceConfig):
            self._config = config
        else:
            cfg_dict = dict(config or {})
            cfg_dict.update(kwargs)
            # reference accepts mp_size= at top level
            if "mp_size" in cfg_dict:
                cfg_dict.setdefault("tensor_parallel", {})
                if isinstance(cfg_dict["tensor_parallel"], dict):
                    cfg_dict["tensor_parallel"].setdefault(
                        "tp_size", cfg_dict.pop("mp_size"))
                else:
                    cfg_dict.pop("mp_size")
            self._config = DeepSpeedInferenceConfig(**cfg_dict)

        if mesh is not None:
            mesh_mod.set_mesh(mesh)
        elif not mesh_mod.has_mesh():
            mesh_mod.initialize_mesh(model=self._config.mp_size)
        self.mesh = mesh_mod.get_mesh()
        self.mp_world_size = mesh_mod.get_model_parallel_world_size()

        self.module = model
        self.dtype = self._config.jnp_dtype()
        self._params_host = model_parameters
        self.params = None
        self._param_shardings = None
        self._rules: Optional[ShardingRules] = None
        self._jit_logits = None
        self._jit_prefill = None
        self._jit_decode = None
        self._jit_prefill_gen = None
        self._jit_decode_scan = None
        self._jit_sample = None
        self._decode_fn = None
        self._jit_verify_k = None
        self._jit_prefill_chunk = None
        self._decode_scan_execs = {}  # aval-keyed AOT decode executables
        self._cache = None
        self._cache_batch = None
        log_dist(f"InferenceEngine: tp={self.mp_world_size} dtype={self._config.dtype}",
                 ranks=[0])

    # ------------------------------------------------------------------
    def _ensure_params(self, input_ids) -> None:
        if self.params is not None:
            return
        if self._params_host is None:
            if not hasattr(self.module, "init"):
                raise ValueError("pass model_parameters= for non-flax models")
            rng = jax.random.PRNGKey(0)
            variables = self.module.init(
                {"params": rng},
                jnp.asarray(input_ids[:1]), method=self.module.logits)
            self._params_host = variables["params"]
        self._finalize_params()

    def _finalize_params(self) -> None:
        def cast(p):
            p = jnp.asarray(p)
            return p.astype(self.dtype) if jnp.issubdtype(p.dtype, jnp.floating) \
                else p

        params = jax.tree_util.tree_map(cast, self._params_host)
        self._rules = replace_module(
            self.module, params=params, tp_size=self.mp_world_size,
            injection_policy=self._config.injection_policy)

        def leaf_sharding(path, leaf):
            spec = self._rules.spec_for(_path_str(path))
            if spec is None or len(spec) != np.ndim(leaf):
                spec = PartitionSpec(*([None] * np.ndim(leaf)))
            # canonicalize through the axis-rules guard: size-1 mesh axes
            # and axes that don't divide the dim collapse to replicated,
            # and trailing Nones are stripped so equivalent placements
            # produce IDENTICAL NamedShardings (P() vs P(None,'model') on
            # a 1-wide axis would otherwise fork jit executables)
            spec = physical_spec(tuple(spec), np.shape(leaf), self.mesh)
            return NamedSharding(self.mesh, spec)

        if self._use_int8_compute():
            params = self._quantize_structured(params)
            self._quant_scales = None
        else:
            params, self._quant_scales = self._maybe_quantize(params)
        self._param_shardings = jax.tree_util.tree_map_with_path(leaf_sharding, params)
        self.params = jax.device_put(params, self._param_shardings)
        if hasattr(self.module, "logits"):
            self._build_jits()

    # ------------------------------------------------------------------
    # weight-only int8 (quant.enabled or dtype=int8): kernels live in HBM
    # as int8 + per-group fp32 scales; every compiled function dequantizes
    # IN-JIT, so XLA fuses the int8→bf16 convert + scale into the consuming
    # matmul's operand read (≅ the reference's int8 inference tier,
    # csrc/quantization + weight_quantizer.py). Where weights are read once
    # per dispatch (per-step decode, prefill) this halves weight HBM
    # traffic (~1.5x measured, BASELINE.md); inside the whole-loop decode
    # scan XLA hoists the dequant, so the win there is at-rest/transport
    # footprint, not bandwidth.
    # ------------------------------------------------------------------
    def _quant_enabled(self) -> bool:
        return self._config.quant.enabled or \
            "int8" in str(self._config.dtype)

    # -- int8 COMPUTE tier -------------------------------------------------
    # When the served module is the unified TransformerLM family, int8
    # doesn't stop at storage: the Dense layers are swapped for QuantDense
    # (int8 kernel + f32 per-output-channel scale) and every matmul runs
    # the Pallas dequant-GEMM (ops/quantization/int8_matmul.py) — the
    # reference's fused csrc/transformer/inference dequantize path.
    # Weights stream from HBM as int8 even inside the whole-loop decode
    # scan, where the storage tier's XLA dequant would be hoisted into a
    # materialized bf16 copy. TP>1 keeps the storage tier (the Pallas call
    # is not yet partition-annotated for GSPMD).
    # ----------------------------------------------------------------------
    # single source of truth for which modules are QuantDense-convertible
    from ..ops.quantization.convert import DENSE_KEYS as _INT8_DENSE_KEYS

    def _use_int8_compute(self) -> bool:
        cfg = getattr(self.module, "config", None)
        return (self._quant_enabled()
                and self._config.quant.bits == 8
                and self.mp_world_size == 1
                # QuantDense computes in bf16; honor an explicit f32
                # request by keeping the dequant storage tier instead
                and self.dtype == jnp.bfloat16
                and hasattr(cfg, "int8_weights")
                and not getattr(cfg, "int8_weights"))

    def _quantize_structured(self, params):
        """bf16 param tree -> QuantDense tree (int8 kernel, f32 scale) for
        every Dense in the LM; rebuilds the serving module with
        ``int8_weights=True``."""
        import dataclasses

        from ..ops.quantization.convert import quantize_lm_params

        # the vocab projection stays full precision (int8_head defaults
        # off) — same tier shape as ZeroInferenceEngine, so dtype=int8
        # yields identical output-head numerics in both engines
        head_keys = {"lm_head"} if not getattr(
            self.module.config, "int8_head", False) else set()
        qparams, n_dense = quantize_lm_params(
            params, dense_keys=self._INT8_DENSE_KEYS - head_keys)
        self._serve_module = self.module.clone(config=dataclasses.replace(
            self.module.config, int8_weights=True))
        log_dist(f"inference int8 compute tier: {n_dense} Dense kernels -> "
                 "QuantDense (Pallas dequant-GEMM)", ranks=[0])
        return qparams

    def _maybe_quantize(self, params):
        if not self._quant_enabled():
            return params, None
        from ..runtime.weight_quantizer import WeightQuantization

        qcfg = self._config.quant
        wq = WeightQuantization(num_bits=qcfg.bits)
        scales: dict = {}

        def visit(path, leaf):
            if np.ndim(leaf) < 2 or not jnp.issubdtype(
                    jnp.asarray(leaf).dtype, jnp.floating):
                return leaf
            size = int(np.prod(np.shape(leaf)))
            groups = size // qcfg.group_size \
                if qcfg.group_size and size % qcfg.group_size == 0 else 1
            q, s = wq.quantize_value(np.asarray(leaf, np.float32), groups)
            scales[_path_str(path)] = jnp.asarray(s)
            return jnp.asarray(q)

        qparams = jax.tree_util.tree_map_with_path(visit, params)
        log_dist(f"inference weight quantization: int{qcfg.bits}, "
                 f"{len(scales)} kernels, group_size={qcfg.group_size}",
                 ranks=[0])
        return qparams, scales

    def _dequant(self, params):
        """Traced: restore compute-dtype kernels from int8 + scales."""
        if self._quant_scales is None:
            return params
        scales = self._quant_scales
        dtype = self.dtype

        def visit(path, leaf):
            key = _path_str(path)
            if key not in scales:
                return leaf
            s = scales[key]
            flat = leaf.astype(jnp.float32).reshape(s.shape[0], -1) * s
            return flat.reshape(leaf.shape).astype(dtype)

        return jax.tree_util.tree_map_with_path(visit, params)

    def _build_jits(self) -> None:
        module = getattr(self, "_serve_module", None) or self.module
        dequant = self._dequant

        def logits_fn(params, input_ids):
            return module.apply({"params": dequant(params)}, input_ids,
                                method=module.logits)

        def prefill_fn(params, input_ids):
            out, vars_ = module.apply(
                {"params": dequant(params)}, input_ids, method=module.prefill,
                mutable=["cache"])
            return out, vars_["cache"]

        # generation-only prefill: last-position logits (the full
        # (B, T, V) fp32 prompt logits are the largest prefill buffer
        # and bound the servable batch at long context — BASELINE.md)
        prefill_gen = getattr(module, "prefill_last", None)

        def prefill_last_fn(params, input_ids):
            out, vars_ = module.apply(
                {"params": dequant(params)}, input_ids,
                method=prefill_gen, mutable=["cache"])
            return out, vars_["cache"]

        def prefill_at_fn(params, input_ids, last_pos):
            # serving-path prefill: prompts are right-padded to a shape
            # bucket (bounds recompiles across arbitrary prompt lengths)
            # and ``last_pos`` projects the true last prompt position
            out, vars_ = module.apply(
                {"params": dequant(params)}, input_ids, last_pos,
                method=prefill_gen, mutable=["cache"])
            return out, vars_["cache"]

        chunk_gen = getattr(module, "prefill_chunk", None)

        def prefill_chunk_fn(params, cache, ids, slot, start, length,
                             last_idx):
            """One bounded prefill chunk DIRECTLY into slot ``slot`` of
            the slot-pooled cache: dynamic-slice the target row out
            (batch axis 1 of the (L, B, ...) leaves), run the (1, C)
            chunked forward against it at offset ``start``, and
            dynamic-update-slice the row back with the slot's index set
            to ``start + length`` (the TRUE new prefill offset — the
            chunk ran at padded width C). Only the target row is ever
            written, so live neighbours can't be clobbered by the
            chunk's C-wide writes, and slot/start/length are traced —
            ONE compiled program covers every slot at every offset."""
            cs = cache["cache_store"]
            slot = jnp.asarray(slot, jnp.int32)
            start = jnp.asarray(start, jnp.int32)
            row = {k: jax.lax.dynamic_slice_in_dim(v, slot, 1, 1)
                   for k, v in cs.items() if k != "index"}
            row["index"] = start[None]
            out, vars_ = module.apply(
                {"params": dequant(params), "cache": {"cache_store": row}},
                ids, start[None], last_idx, method=chunk_gen,
                mutable=["cache"])
            new = vars_["cache"]["cache_store"]

            def write(dst, src):
                idx = (jnp.zeros((), jnp.int32), slot) + \
                    (jnp.zeros((), jnp.int32),) * (dst.ndim - 2)
                return jax.lax.dynamic_update_slice(
                    dst, src.astype(dst.dtype), idx)

            merged = {k: write(cs[k], new[k]) for k in cs if k != "index"}
            merged["index"] = cs["index"].at[slot].set(
                start + jnp.asarray(length, jnp.int32))
            return out, {"cache_store": merged}

        def decode_fn(params, cache, token, pos):
            out, vars_ = module.apply(
                {"params": dequant(params), "cache": cache}, token, pos,
                method=module.decode, mutable=["cache"])
            return out, vars_["cache"]

        def sample_fn(logits, rng, temperature, top_k, top_p, greedy):
            last = logits[:, -1, :].astype(jnp.float32)
            scaled = _filter_logits(last, temperature, top_k, top_p)
            sampled = jax.random.categorical(rng, scaled, axis=-1)
            return jnp.where(greedy, jnp.argmax(last, axis=-1), sampled)

        def decode_scan_fn(params, cache, token, pos, rng, temperature,
                           greedy, n_steps, top_k, top_p):
            """The whole decode loop as ONE compiled program — the TPU
            equivalent of the reference's CUDA-graph capture/replay
            (inference/engine.py:532,551): a single dispatch generates
            ``n_steps`` tokens, so per-step host/dispatch latency vanishes."""

            def body(carry, _):
                cache, token, pos, rng = carry
                logits, cache = decode_fn(params, cache, token[:, None], pos)
                rng, sub = jax.random.split(rng)
                nxt = sample_fn(logits, sub, temperature, top_k, top_p,
                                greedy).astype(jnp.int32)
                return (cache, nxt, pos + 1, rng), nxt

            (cache, token, pos, rng), toks = jax.lax.scan(
                body, (cache, token, pos, rng), None, length=n_steps)
            return cache, toks.T  # (B, n_steps)

        # the traced decode body is kept for composition: the speculative
        # verify program (serving/spec_decode) closes over it
        self._decode_fn = decode_fn
        self._jit_logits = jax.jit(logits_fn)
        self._jit_prefill = jax.jit(prefill_fn)
        self._jit_prefill_gen = jax.jit(prefill_last_fn) \
            if prefill_gen is not None else self._jit_prefill
        self._jit_prefill_at = jax.jit(prefill_at_fn) \
            if prefill_gen is not None else None
        self._jit_prefill_chunk = jax.jit(prefill_chunk_fn,
                                          donate_argnums=(1,)) \
            if chunk_gen is not None else None
        self._jit_decode = jax.jit(decode_fn, donate_argnums=(1,))
        self._jit_sample = jax.jit(sample_fn, static_argnums=(3, 4))
        self._jit_decode_scan = jax.jit(decode_scan_fn,
                                        donate_argnums=(1,),
                                        static_argnums=(7, 8, 9))

    # ------------------------------------------------------------------
    def forward(self, input_ids, *args, **kwargs):
        """Full-context logits for LM modules; non-LM modules (no
        ``logits`` method — e.g. the diffusion family) run a generic
        compiled apply over the given arguments (≅ reference
        engine.forward, inference/engine.py:592, which serves any wrapped
        module)."""
        if not hasattr(self.module, "logits"):
            return self._generic_forward(input_ids, *args, **kwargs)
        input_ids = jnp.asarray(input_ids)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        self._ensure_params(input_ids)
        return self._jit_logits(self.params, input_ids)

    def _generic_forward(self, *args, **kwargs):
        args = tuple(jnp.asarray(a) for a in args)
        if self.params is None:
            if self._params_host is None:
                if not hasattr(self.module, "init"):
                    raise ValueError(
                        "pass model_parameters= for non-flax models")
                self._params_host = self.module.init(
                    {"params": jax.random.PRNGKey(0)}, *args,
                    **kwargs)["params"]
            self._finalize_params()
        # kwargs are threaded into the compiled apply (keys are static; a
        # new key set recompiles)
        kw_keys = tuple(sorted(kwargs))
        if getattr(self, "_jit_generic_keys", None) != kw_keys:
            self._jit_generic_keys = kw_keys
            self._jit_generic = jax.jit(
                lambda p, a, kv: self.module.apply(
                    {"params": self._dequant(p)}, *a,
                    **dict(zip(kw_keys, kv))))
        return self._jit_generic(self.params, args,
                                 tuple(kwargs[k] for k in kw_keys))

    __call__ = forward

    def _compile_decode_scan(self, cache_aval, batch, n_steps, top_k, top_p):
        """AOT-compile the whole-decode program from avals only (no cache
        buffer live), caching the executable per signature. Returns None
        when AOT lowering is unavailable so generate() falls back to the
        plain jit dispatch."""
        if self.mp_world_size != 1:
            # TP caches come out of prefill sharded over the model axis;
            # lowering with replicated avals would produce an executable
            # that can never match (an expensive dead compile) — skip and
            # use the plain jit dispatch
            return None
        leaves = jax.tree_util.tree_leaves(cache_aval)
        key = (jax.tree_util.tree_structure(cache_aval),
               tuple((l.shape, str(l.dtype)) for l in leaves),
               batch, n_steps, top_k, top_p)
        if key in self._decode_scan_execs:
            return self._decode_scan_execs[key]
        try:
            rep = NamedSharding(mesh_mod.get_mesh(), PartitionSpec())
            p_sds = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=a.sharding),
                self.params)
            c_sds = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=rep), cache_aval)
            rng_shape = jax.eval_shape(jax.random.PRNGKey, 0)
            lowered = self._jit_decode_scan.lower(
                p_sds, c_sds,
                jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=rep),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
                jax.ShapeDtypeStruct(rng_shape.shape, rng_shape.dtype,
                                     sharding=rep),
                jax.ShapeDtypeStruct((), jnp.float32, sharding=rep),
                jax.ShapeDtypeStruct((), jnp.bool_, sharding=rep),
                n_steps, top_k, top_p)
            compiled = lowered.compile()
        except Exception as e:  # noqa: BLE001 — fall back to plain jit
            # do NOT cache the failure: a transient remote-compile outage
            # would otherwise disable the precompile path for the
            # engine's lifetime; the next generate() retries
            log_dist(f"decode-scan AOT precompile unavailable ({e}); "
                     f"falling back to jit dispatch", ranks=[0])
            return None
        self._decode_scan_execs[key] = compiled
        return compiled

    def kv_cache_spec(self):
        """The served module's declared KV-cache contract, or None when it
        doesn't declare one (foreign modules). The serving subsystem sizes
        its slot pool from this."""
        module = getattr(self, "_serve_module", None) or self.module
        spec_fn = getattr(module, "kv_cache_spec", None)
        if not callable(spec_fn):
            return None
        try:
            return spec_fn()
        except Exception:  # noqa: BLE001 — foreign modules may need state
            return None

    def _declared_kv_capacity(self) -> Optional[int]:
        spec = self.kv_cache_spec()
        cap = getattr(spec, "max_seq_len", None)
        return int(cap) if cap is not None else None

    # ------------------------------------------------------------------
    def prefill_chunk(self, cache, input_ids, slot, start, length,
                      last_idx):
        """Process one fixed-width prefill chunk into row ``slot`` of the
        slot-pooled ``cache`` at offset ``start`` (see the jitted body in
        ``_build_jits``). ``input_ids`` is (1, C) int32 right-padded,
        ``length`` the TRUE token count in the chunk, ``last_idx`` the
        position (within the chunk) to project — only meaningful on the
        final chunk, whose logits seed the first sampled token. Returns
        ``(logits (1, 1, V), cache)``; the cache operand is donated
        (updated in place in HBM) and comes back with the slot's index
        at ``start + length``."""
        if self._jit_prefill_chunk is None:
            raise ValueError("prefill_chunk requires a module exposing "
                             "prefill_chunk(input_ids, start_pos, "
                             "last_idx); the unified TransformerLM "
                             "family does")
        return self._jit_prefill_chunk(
            self.params, cache, jnp.asarray(input_ids, jnp.int32),
            jnp.asarray(slot, jnp.int32), jnp.asarray(start, jnp.int32),
            jnp.asarray(length, jnp.int32), jnp.asarray(last_idx, jnp.int32))

    def verify_k(self, cache, tokens, pos, draft, draft_len, rng,
                 temperature, greedy, top_k: int, top_p: float):
        """Speculative verification: score K draft positions for every
        row in ONE fixed-shape chunked-decode forward and run acceptance
        in the same compiled program (greedy accept-prefix, or lossless
        rejection sampling under the serving sampler's filtered
        distribution for ``do_sample``).

        ``tokens`` is (B, K+1) int32 — [current_token, draft_0..K-1] per
        row; ``pos`` (B,) int32 per-slot cache offsets; ``draft`` (B, K);
        ``draft_len`` (B,) int32 in [0, K] (0 = plain decode for that
        row: dead or non-speculating slots ride along masked). The cache
        operand is donated (updated in place in HBM) and comes back with
        all K+1 positions written for every row — the caller rolls back
        rejected positions by per-slot ``index`` masking
        (:meth:`SlotPool.advance`), never a reshape.

        Returns ``(cache, out (B, K+1) int32, n_emit (B,) int32)``: row
        ``i`` emits ``out[i, :n_emit[i]]`` — the accepted draft prefix
        plus the bonus/correction token (always >= 1 per step).
        """
        if self._decode_fn is None:
            raise ValueError("verify_k requires an LM module with a "
                             "decode() method (build jits first)")
        if self._jit_verify_k is None:
            from ..serving.spec_decode.verify import make_verify_fn

            self._jit_verify_k = jax.jit(
                make_verify_fn(self._decode_fn, _filter_logits),
                donate_argnums=(1,), static_argnums=(9, 10))
        return self._jit_verify_k(self.params, cache, tokens, pos, draft,
                                  draft_len, rng, temperature, greedy,
                                  int(top_k), float(top_p))

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: Optional[float] = None,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 **kwargs):
        """Autoregressive generation with KV cache (≅ reference
        engine._generate, inference/engine.py:620).

        Returns int32 array (B, T_prompt + n_generated) — prompt + new
        tokens, truncated at ``eos_token_id`` if every row finished early.
        """
        cfg = self._config
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        B, T = input_ids.shape
        if max_new_tokens <= 0:
            return np.asarray(input_ids)
        max_len = getattr(self.module.config, "max_seq_len", None)
        if max_len is not None and T + max_new_tokens > max_len:
            raise ValueError(
                f"prompt({T}) + max_new_tokens({max_new_tokens}) exceeds the "
                f"model's max_seq_len({max_len}) KV-cache capacity")
        self._ensure_params(input_ids)

        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k
        top_p = cfg.top_p if top_p is None else top_p
        greedy = jnp.asarray(not do_sample)

        # Cache avals from a shape-only prefill: the decode-program compile
        # happens BEFORE any cache buffer lives. The allocated KV capacity
        # comes from the module's DECLARED kv_cache_spec when it has one
        # (the allocation contract — ADVICE r5; the serving slot pool
        # consumes the same spec), falling back to the last dim of ndim>=4
        # cache leaves (positions-minor layout) only for foreign modules
        # that declare nothing. Steps past capacity would write out of
        # bounds (silently clamped by JAX today, but fragile); fail loudly.
        _, cache_aval = jax.eval_shape(self._jit_prefill_gen, self.params,
                                       input_ids)
        cache_cap = self._declared_kv_capacity()
        if cache_cap is None:
            cache_cap = max((x.shape[-1]
                             for x in jax.tree_util.tree_leaves(cache_aval)
                             if getattr(x, "ndim", 0) >= 4), default=None)
        caps = [c for c in (max_len, cache_cap) if c is not None]
        capacity = min(caps) if caps else None
        if capacity is not None and T + max_new_tokens > capacity:
            raise ValueError(
                f"prompt({T}) + max_new_tokens({max_new_tokens}) exceeds the "
                f"allocated KV-cache capacity({capacity})")

        # NOTE generate() deliberately does NOT pass a decode block hint:
        # an A/B that derived the block from the generation budget
        # (preferred_block_for(T + max_new_tokens), so live 1536 in an 8k
        # cache took the 1024 block) measured EVERY arm 5-15% slower —
        # decode at these shapes is grid-overhead bound, not dead-row
        # bound (the index-map clamp already elides dead-block DMA), so
        # fewer, larger grid steps win even when the last live block is
        # mostly dead (BASELINE.md round-5 KV e2e section). Callers with
        # measured wins at their own shapes can drive
        # module.decode(block_hint=...) directly.
        decode_exec = None
        if eos_token_id is None:
            # whole-loop compile (CUDA-graph analog): ONE dispatch for the
            # entire decode — per-token host/tunnel latency disappears.
            # n_steps is static, so bucket it (next power of two, capped by
            # the KV capacity) to bound recompiles across varying budgets;
            # the extra steps' outputs are sliced off.
            n_steps = max_new_tokens - 1
            bucket = 1
            while bucket < n_steps:
                bucket *= 2
            if capacity is not None:
                bucket = min(bucket, capacity - T - 1)
            bucket = max(bucket, n_steps)
            # AOT-compile the decode program NOW, before the prefill cache
            # exists: the remote compile checks the program's HBM budget
            # against FREE memory without crediting the dispatch-time
            # donation of the cache carries, so compiling with buffers
            # live needs transient 2x-cache headroom (the
            # kv_capacity_results.json boundary finding). Donation is part
            # of the lowering, so the dispatch itself aliases as usual.
            decode_exec = self._compile_decode_scan(
                cache_aval, B, bucket, int(top_k), float(top_p))

        logits, cache = self._jit_prefill_gen(self.params, input_ids)
        rng = jax.random.PRNGKey(seed)
        rng, sub = jax.random.split(rng)
        token = self._jit_sample(logits, sub, jnp.asarray(temperature, jnp.float32),
                                 int(top_k), float(top_p), greedy)

        if eos_token_id is None:
            args = (self.params, cache, token.astype(jnp.int32),
                    jnp.asarray(T, jnp.int32), rng,
                    jnp.asarray(temperature, jnp.float32), greedy)
            rest = None
            if decode_exec is not None:
                # small args must match the replicated shardings the
                # executable was lowered with; the cache comes straight
                # from prefill — if its layout disagrees (e.g. TP-sharded
                # caches), fall back to the plain jit dispatch
                rep = NamedSharding(mesh_mod.get_mesh(), PartitionSpec())
                try:
                    placed = (args[0], args[1]) + tuple(
                        jax.device_put(a, rep) for a in args[2:])
                    _, rest = decode_exec(*placed)
                except ValueError:
                    rest = None
            if rest is None:
                _, rest = self._jit_decode_scan(
                    *args, bucket, int(top_k), float(top_p))
            toks = np.concatenate([np.asarray(token)[:, None],
                                   np.asarray(rest)[:, :n_steps]], axis=1)
        else:
            # eager loop with pipelined eos check: step j+1 is DISPATCHED
            # before step j's tokens are pulled to the host, so the eos
            # fetch overlaps the in-flight decode instead of serializing
            # every iteration on a device round-trip. When the check says
            # everyone finished, the just-dispatched step's token is
            # dropped — output width and values match the serial loop
            # bitwise (the speculative step consumed one rng split, but
            # nothing after the break reads the stream).
            dev_out = [token]
            finished = np.zeros((np.shape(input_ids)[0],), bool)
            pos = T
            for _ in range(max_new_tokens - 1):
                logits, cache = self._jit_decode(
                    self.params, cache, token[:, None],
                    jnp.asarray(pos, jnp.int32))
                rng, sub = jax.random.split(rng)
                nxt = self._jit_sample(
                    logits, sub, jnp.asarray(temperature, jnp.float32),
                    int(top_k), float(top_p), greedy)
                # host sync on the PREVIOUS token while this step runs
                finished |= np.asarray(token) == eos_token_id
                if finished.all():
                    break
                token = nxt
                dev_out.append(token)
                pos += 1
            toks = np.stack([np.asarray(t) for t in dev_out], axis=1)
        if eos_token_id is not None:
            # clamp everything after each row's first eos to eos
            hit = np.cumsum(toks == eos_token_id, axis=1) > 0
            after = np.roll(hit, 1, axis=1)
            after[:, 0] = False
            toks = np.where(after, eos_token_id, toks)
        return np.concatenate([np.asarray(input_ids), toks], axis=1)

    # ------------------------------------------------------------------
    def throughput(self, input_ids, max_new_tokens: int = 64) -> Dict[str, float]:
        """Decode-throughput probe (tokens/s) used by bench/autotuning."""
        t0 = time.perf_counter()
        toks = self.generate(input_ids, max_new_tokens=max_new_tokens)
        dt = time.perf_counter() - t0
        n_new = toks.shape[1] - np.shape(input_ids)[-1]
        return {"tokens_per_sec": n_new * toks.shape[0] / dt, "elapsed_s": dt}
