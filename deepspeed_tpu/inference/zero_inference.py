"""ZeRO-Inference — weight streaming for models larger than HBM.

Capability parity with reference ZeRO-Inference
(docs/_posts/2022-09-10-zero-inference.md; engine hooks at
inference/engine.py:336,449): model weights live in HOST memory (or a
memory-mapped checkpoint) and stream to the device one transformer layer
at a time, so the device-resident footprint is O(2 layers), not O(model).
Throughput-oriented by design — with a large token batch each layer's
matmuls amortize its weight transfer (the reference's "7 TFLOPs per
GPT3-layer per token-batch" argument).

TPU-native mechanics: the per-layer apply is ONE jitted function reused
for every layer (identical shapes → single compile), and JAX's async
dispatch gives upload/compute overlap for free — ``device_put`` of layer
``i+1`` is enqueued before the compute of layer ``i`` blocks (double
buffering without streams, the role pinned-buffer prefetch plays in the
reference's AIO pipeline).

Works with :class:`deepspeed_tpu.models.transformer_lm.TransformerLM`
params (scan-stacked blocks with a leading layer axis).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer_lm import TransformerBlock, TransformerConfig
from ..utils.logging import log_dist
from ..utils.streaming import LayerWireFormat


def _slice_layer(stacked: Any, i: int) -> Any:
    """Layer ``i`` of scan-stacked params (leading layer axis per leaf)."""
    return jax.tree_util.tree_map(lambda a: np.asarray(a[i]), stacked)


class ZeroInferenceEngine:
    """Full-context scoring with layer-streamed weights.

    ``params_host``: the TransformerLM param pytree, host-resident
    (numpy arrays or np.memmap views into a checkpoint file).
    """

    def __init__(self, config: TransformerConfig, params_host: Dict,
                 dtype=jnp.bfloat16, prefetch: int = 1, pack: bool = True,
                 int8: bool = False):
        if int8 and not config.int8_weights:
            # int8 ZeRO-Inference: quantize the Dense kernels host-side
            # (QuantDense layout) so each streamed layer is ~half the
            # bytes AND the dequant runs inside the Pallas GEMM on chip.
            # The head lives in the always-resident tier and stays full
            # precision unless ``config.int8_head`` opts it in (same tier
            # shape as the resident engine); head_fn dequantizes it.
            import dataclasses

            from ..ops.quantization.convert import DENSE_KEYS, quantize_lm_params

            head_keys = set() if config.int8_head else {"lm_head"}
            params_host, n_dense = quantize_lm_params(
                params_host, dense_keys=DENSE_KEYS - head_keys)
            config = dataclasses.replace(config, int8_weights=True)
            log_dist(f"ZeroInference int8 tier: {n_dense} Dense kernels -> "
                     "QuantDense (streamed int8-at-rest)", ranks=[0])
        self.config = config
        self.dtype = dtype
        self.prefetch = max(0, prefetch)
        self._host = params_host
        self._stacked = params_host["blocks"]["block"]
        self.n_layer = config.n_layer
        # pack: ship each layer as ONE contiguous buffer instead of one
        # transfer per leaf — per-transfer latency (host↔device link
        # round-trips) would otherwise dominate the stream for trees with
        # many small leaves; leaves are re-sliced on device by a jitted
        # unpack (an HBM-local copy)
        self.pack = pack
        # the packed buffer is raw BYTES, so any leaf-dtype mix ships as
        # one transfer (bf16 checkpoints, int8 QuantDense kernels with
        # f32 scales, ...). Float leaves are converted to the engine
        # compute dtype at stage time — except "scale" leaves, which are
        # per-channel quantization/norm scales that stay full precision
        # (utils/streaming.py holds the shared wire format).
        self._wire = LayerWireFormat(
            _slice_layer(self._stacked, 0), dtype,
            keep_dtype=lambda path, leaf:
            getattr(path[-1], "key", None) == "scale")
        self._layer_treedef = self._wire.treedef
        self._leaf_shapes = self._wire.shapes
        self._leaf_wire_dtypes = self._wire.wire_dtypes
        self._leaf_nbytes = self._wire.nbytes

        # small always-resident pieces: embeddings, final norm, head
        def put_small(name):
            if name not in params_host:
                return None
            return jax.device_put(jax.tree_util.tree_map(
                lambda a: jnp.asarray(a, dtype) if jnp.issubdtype(
                    np.asarray(a).dtype, jnp.floating) else jnp.asarray(a),
                params_host[name]))

        self._small = {name: put_small(name)
                       for name in ("embed_tokens", "embed_pos", "embed_ln",
                                    "ln_f", "lm_head")
                       if name in params_host}

        cfg = config
        block = TransformerBlock(cfg)

        def block_fn(layer_params, x):
            if self.pack:
                layer_params = self._unpack(layer_params)
            return block.apply({"params": layer_params}, x, False, True)[0]

        # NOTE: no input donation here (neither the layer buffer nor the
        # activation). Buffers are freed by refcount (`buffers.pop` +
        # `del`); in isolated A/B tests on the axon-tunneled runtime,
        # put->consume loops with a donated consumed input degraded
        # subsequent host->device transfers ~100x after ~15 iterations,
        # while the identical loop without donation held ~1.5 GB/s.
        self._jit_block = jax.jit(block_fn)

        from ..models.transformer_lm import make_layer_kv_cache

        def cached_block_init_fn(layer_params, x):
            # first (prefill) pass: build this layer's zeroed cache and
            # thread it explicitly — the block takes/returns the cache as
            # a value (carry-DUS design; layout/dtype stay the model's
            # concern via make_layer_kv_cache). "prefill" mode (the
            # start == 0 contract this fn guarantees) attends over the
            # fresh prompt k/v — O(T) memory, never the (B, H, T, S)
            # allocated-cache score tensor that OOMs at long prompts.
            if self.pack:
                layer_params = self._unpack(layer_params)
            cache = dict(make_layer_kv_cache(cfg, x.shape[0]),
                         start=jnp.zeros((), jnp.int32))
            out, new_cache = block.apply({"params": layer_params}, x,
                                         "prefill", True, cache)
            new_cache.pop("start", None)
            return out, new_cache

        def cached_block_fn(layer_params, cache, x, start):
            if self.pack:
                layer_params = self._unpack(layer_params)
            out, new_cache = block.apply(
                {"params": layer_params}, x, True, True,
                dict(cache, start=start))
            new_cache.pop("start", None)
            return out, new_cache

        self._jit_cached_block_init = jax.jit(cached_block_init_fn)
        # the cache IS donated: it is device-resident and round-trips
        # through this same jit (in-place update, no full-cache copy per
        # layer per token). The no-donation NOTE above concerns
        # host->device-transferred buffers only.
        self._jit_cached_block = jax.jit(cached_block_fn,
                                         donate_argnums=(1,))

        from ..models.transformer_lm import _norm

        def embed_fn(emb, pos_emb, emb_ln, ids, start):
            B, T = ids.shape
            table = emb["embedding"]
            x = jnp.take(table, ids, axis=0)
            if pos_emb is not None:
                pos = jnp.broadcast_to(start + jnp.arange(T)[None], (B, T))
                x = x + jnp.take(pos_emb["embedding"], pos, axis=0)
            if emb_ln is not None:
                # bloom-family embedding layernorm (transformer_lm.py:332)
                x = _norm(cfg, "embed_ln").apply({"params": emb_ln}, x)
            return x

        self._jit_embed = jax.jit(embed_fn)

        def head_fn(emb, ln_f_params, lm_head, x):
            ln = _norm(cfg, "ln_f")
            x = ln.apply({"params": ln_f_params}, x)
            if lm_head is not None:
                kern = lm_head["kernel"].astype(jnp.float32)
                if "scale" in lm_head:
                    # int8_head tier: QuantDense layout (padded int8 kernel
                    # + per-column scale); dequant on the resident copy and
                    # slice off the lane padding
                    kern = (kern * lm_head["scale"])[:, :cfg.vocab_size]
                return x.astype(jnp.float32) @ kern
            return x.astype(jnp.float32) @ \
                emb["embedding"].T.astype(jnp.float32)

        self._jit_head = jax.jit(head_fn)
        total = sum(np.asarray(l).nbytes for l in
                    jax.tree_util.tree_leaves(params_host))
        per_layer = sum(np.asarray(l).nbytes for l in
                        jax.tree_util.tree_leaves(self._stacked)) \
            // max(self.n_layer, 1)
        log_dist(f"ZeroInference: {total / 1e9:.2f} GB weights host-resident,"
                 f" streaming {per_layer / 1e6:.1f} MB/layer "
                 f"(prefetch={self.prefetch})", ranks=[0])

    def _put_layer(self, i: int):
        layer = _slice_layer(self._stacked, i)
        if not self.pack:
            # same wire-dtype rule as the packed path (floats -> compute
            # dtype, "scale" leaves and non-floats keep storage dtype)
            leaves = jax.tree_util.tree_leaves(layer)
            conv = [np.asarray(l, wdt) for l, wdt in
                    zip(leaves, self._leaf_wire_dtypes)]
            return jax.device_put(jax.tree_util.tree_unflatten(
                self._layer_treedef, conv))
        leaves = jax.tree_util.tree_leaves(layer)
        # rotating staging buffers, NOT a fresh array per layer: (a) the
        # runtime retains a host reference per staged transfer, so fresh
        # buffers grow RSS by the whole model per pass (observed OOM at
        # 48 GB streamed); (b) re-put of the same host buffer rides the
        # pinned-transfer fast path (~1.6 GB/s vs ~0.6 GB/s first-put on
        # the tunneled runtime). prefetch+2 buffers guarantee no in-flight
        # transfer shares a buffer with the layer being staged.
        if not hasattr(self, "_staging"):
            n_buf = self.prefetch + 2
            total = sum(self._leaf_nbytes)
            self._staging = [np.empty(total, np.uint8) for _ in range(n_buf)]
            self._staging_dev = [None] * n_buf
            self._staging_i = 0
        slot = self._staging_i
        self._staging_i = (self._staging_i + 1) % len(self._staging)
        if self._staging_dev[slot] is not None:
            # the slot's previous transfer must be on-device before its
            # host buffer is overwritten (dispatch runs ahead of execution)
            self._staging_dev[slot].block_until_ready()
        # release guard refs for transfers that already landed, so the
        # device footprint stays O(prefetch+1 layers): the consumer
        # (`forward`'s buffers dict) is the only remaining owner
        for s, dev in enumerate(self._staging_dev):
            if dev is not None and s != slot:
                try:
                    if dev.is_ready():
                        self._staging_dev[s] = None
                except AttributeError:
                    break  # runtime without is_ready: keep refs as guards
        buf = self._staging[slot]
        self._wire.pack_into(
            jax.tree_util.tree_unflatten(self._layer_treedef, leaves), buf)
        # CPU backend: device_put ZERO-COPIES host numpy, so a reused
        # staging buffer would alias a live device array — hand it a
        # private copy there (tests-only path; real accelerators copy on
        # transfer and keep the rotating-buffer RSS/pinning wins)
        uni = self._wire.uniform_dtype
        if uni is not None:
            # dtype-uniform layer (plain bf16 checkpoints): ship TYPED and
            # unpack by slice+reshape — the byte-path's (N, itemsize)
            # bitcast reshape tiles catastrophically on real TPUs
            buf = buf.view(uni)
        payload = buf.copy() if jax.default_backend() == "cpu" else buf
        dev = jax.device_put(payload)
        self._staging_dev[slot] = dev
        return dev

    def _unpack(self, flat):
        """Traced: packed buffer -> leaf tree (HBM-local)."""
        if self._wire.uniform_dtype is not None:
            return self._wire.unpack_typed(flat)
        return self._wire.unpack(flat)

    def forward(self, input_ids, layer_times: Optional[list] = None
                ) -> jnp.ndarray:
        """Full-context logits with layer streaming.

        ``layer_times``: optional list; when given, each layer's
        stage+dispatch+execute wall time is appended (the benchmark's
        per-layer instrumentation hook — synchronizes per layer, so only
        pass it when measuring)."""
        import time as _time

        ids = jnp.asarray(input_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        x = self._jit_embed(self._small["embed_tokens"],
                            self._small.get("embed_pos"),
                            self._small.get("embed_ln"), ids,
                            jnp.zeros((), jnp.int32))
        if layer_times is not None:
            x.block_until_ready()
        # pipeline: enqueue next layers' uploads before blocking on compute
        buffers = {}
        for j in range(min(self.prefetch + 1, self.n_layer)):
            buffers[j] = self._put_layer(j)
        for i in range(self.n_layer):
            t0 = _time.perf_counter()
            layer = buffers.pop(i)
            nxt = i + self.prefetch + 1
            if nxt < self.n_layer:
                buffers[nxt] = self._put_layer(nxt)  # async upload
            x = self._jit_block(layer, x)
            # the engine's ref is dropped here; the buffer is freed once
            # the block consumes it and the staging guard's transfer ref
            # is released (see _put_layer)
            del layer
            if layer_times is not None:
                x.block_until_ready()
                layer_times.append(_time.perf_counter() - t0)
        return self._jit_head(self._small["embed_tokens"],
                              self._small["ln_f"],
                              self._small.get("lm_head"), x)

    __call__ = forward

    def score(self, input_ids) -> np.ndarray:
        """Per-sequence mean log-likelihood (throughput-style batch
        scoring, the ZeRO-Inference serving mode). The tail is one jitted
        program — eager op-by-op dispatch over the (B, T, V) logits is
        catastrophically slow on tunneled runtimes."""
        ids = jnp.asarray(input_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        return self.score_logits(self.forward(ids), ids)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, seed: int = 0) -> np.ndarray:
        """Autoregressive generation under weight streaming — the serving
        mode of the reference's ZeRO-Inference (BLOOM-176B generation,
        docs/_posts/2022-09-10-zero-inference.md): weights stay
        host-resident and stream through the chip per step, while the KV
        caches (which DO fit — O(L·B·S·D), not O(params)) stay
        device-resident across the whole generation.

        ``temperature`` 0 = greedy. Returns (B, T_prompt + new) int32.
        """
        cfg = self.config
        ids = jnp.asarray(input_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        B, T = ids.shape
        S = cfg.max_seq_len
        if max_new_tokens <= 0:
            return np.asarray(ids)
        if T + max_new_tokens > S:
            raise ValueError(f"prompt({T}) + max_new_tokens"
                             f"({max_new_tokens}) exceeds max_seq_len({S})")
        caches = [None] * self.n_layer

        if not hasattr(self, "_jit_sample"):
            def sample(logits, rng, temperature):
                last = logits[:, -1, :].astype(jnp.float32)
                greedy = jnp.argmax(last, axis=-1)
                sampled = jax.random.categorical(
                    rng, last / jnp.maximum(temperature, 1e-6), axis=-1)
                return jnp.where(temperature > 0, sampled, greedy) \
                    .astype(jnp.int32)

            self._jit_sample = jax.jit(sample)

        rng = jax.random.PRNGKey(seed)
        temp = jnp.asarray(temperature, jnp.float32)

        def stream_pass(tokens, start, first=False):
            x = self._jit_embed(self._small["embed_tokens"],
                                self._small.get("embed_pos"),
                                self._small.get("embed_ln"), tokens,
                                jnp.asarray(start, jnp.int32))
            buffers = {j: self._put_layer(j)
                       for j in range(min(self.prefetch + 1, self.n_layer))}
            for i in range(self.n_layer):
                layer = buffers.pop(i)
                nxt = i + self.prefetch + 1
                if nxt < self.n_layer:
                    buffers[nxt] = self._put_layer(nxt)
                if first:
                    x, caches[i] = self._jit_cached_block_init(layer, x)
                else:
                    x, caches[i] = self._jit_cached_block(
                        layer, caches[i], x, jnp.asarray(start, jnp.int32))
                del layer
            return self._jit_head(self._small["embed_tokens"],
                                  self._small["ln_f"],
                                  self._small.get("lm_head"), x)

        logits = stream_pass(ids, 0, first=True)  # prefill builds caches
        rng, sub = jax.random.split(rng)
        token = self._jit_sample(logits, sub, temp)
        out = [token]
        for step in range(max_new_tokens - 1):
            logits = stream_pass(token[:, None], T + step)
            rng, sub = jax.random.split(rng)
            token = self._jit_sample(logits, sub, temp)
            out.append(token)
        return np.concatenate([np.asarray(ids)] +
                              [np.asarray(t)[:, None] for t in out], axis=1)

    def score_logits(self, logits, input_ids) -> np.ndarray:
        """The scoring tail over already-computed logits (one jitted
        program + the readback). Split out so callers that must control
        readback ordering (see benchmarks/zero_inference_bench.py) reuse
        the shipped tail instead of re-deriving it."""
        ids = jnp.asarray(input_ids, jnp.int32)
        if not hasattr(self, "_jit_score_tail"):
            def tail(logits, ids):
                logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
                token_ll = jnp.take_along_axis(
                    logp, ids[:, 1:][..., None], axis=-1)[..., 0]
                return jnp.mean(token_ll, axis=-1)

            self._jit_score_tail = jax.jit(tail)
        return np.asarray(self._jit_score_tail(logits, ids))
