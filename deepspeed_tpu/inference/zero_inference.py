"""ZeRO-Inference — weight streaming for models larger than HBM.

Capability parity with reference ZeRO-Inference
(docs/_posts/2022-09-10-zero-inference.md; engine hooks at
inference/engine.py:336,449): model weights live in HOST memory (or a
memory-mapped checkpoint) and stream to the device one transformer layer
at a time, so the device-resident footprint is O(2 layers), not O(model).
Throughput-oriented by design — with a large token batch each layer's
matmuls amortize its weight transfer (the reference's "7 TFLOPs per
GPT3-layer per token-batch" argument).

TPU-native mechanics: the per-layer apply is ONE jitted function reused
for every layer (identical shapes → single compile), and JAX's async
dispatch gives upload/compute overlap for free — ``device_put`` of layer
``i+1`` is enqueued before the compute of layer ``i`` blocks (double
buffering without streams, the role pinned-buffer prefetch plays in the
reference's AIO pipeline).

Works with :class:`deepspeed_tpu.models.transformer_lm.TransformerLM`
params (scan-stacked blocks with a leading layer axis).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer_lm import TransformerBlock, TransformerConfig
from ..utils.logging import log_dist


def _slice_layer(stacked: Any, i: int) -> Any:
    """Layer ``i`` of scan-stacked params (leading layer axis per leaf)."""
    return jax.tree_util.tree_map(lambda a: np.asarray(a[i]), stacked)


class ZeroInferenceEngine:
    """Full-context scoring with layer-streamed weights.

    ``params_host``: the TransformerLM param pytree, host-resident
    (numpy arrays or np.memmap views into a checkpoint file).
    """

    def __init__(self, config: TransformerConfig, params_host: Dict,
                 dtype=jnp.bfloat16, prefetch: int = 1):
        self.config = config
        self.dtype = dtype
        self.prefetch = max(0, prefetch)
        self._host = params_host
        self._stacked = params_host["blocks"]["block"]
        self.n_layer = config.n_layer

        # small always-resident pieces: embeddings, final norm, head
        def put_small(name):
            if name not in params_host:
                return None
            return jax.device_put(jax.tree_util.tree_map(
                lambda a: jnp.asarray(a, dtype) if np.issubdtype(
                    np.asarray(a).dtype, np.floating) else jnp.asarray(a),
                params_host[name]))

        self._small = {name: put_small(name)
                       for name in ("embed_tokens", "embed_pos", "embed_ln",
                                    "ln_f", "lm_head")
                       if name in params_host}

        cfg = config
        block = TransformerBlock(cfg)

        def block_fn(layer_params, x):
            return block.apply({"params": layer_params}, x, False, True)

        self._jit_block = jax.jit(block_fn, donate_argnums=(1,))

        from ..models.transformer_lm import _norm

        def embed_fn(emb, pos_emb, emb_ln, ids):
            B, T = ids.shape
            table = emb["embedding"]
            x = jnp.take(table, ids, axis=0)
            if pos_emb is not None:
                pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
                x = x + jnp.take(pos_emb["embedding"], pos, axis=0)
            if emb_ln is not None:
                # bloom-family embedding layernorm (transformer_lm.py:332)
                x = _norm(cfg, "embed_ln").apply({"params": emb_ln}, x)
            return x

        self._jit_embed = jax.jit(embed_fn)

        def head_fn(emb, ln_f_params, lm_head, x):
            ln = _norm(cfg, "ln_f")
            x = ln.apply({"params": ln_f_params}, x)
            if lm_head is not None:
                return x.astype(jnp.float32) @ \
                    lm_head["kernel"].astype(jnp.float32)
            return x.astype(jnp.float32) @ \
                emb["embedding"].T.astype(jnp.float32)

        self._jit_head = jax.jit(head_fn)
        total = sum(np.asarray(l).nbytes for l in
                    jax.tree_util.tree_leaves(params_host))
        per_layer = sum(np.asarray(l).nbytes for l in
                        jax.tree_util.tree_leaves(self._stacked)) \
            // max(self.n_layer, 1)
        log_dist(f"ZeroInference: {total / 1e9:.2f} GB weights host-resident,"
                 f" streaming {per_layer / 1e6:.1f} MB/layer "
                 f"(prefetch={self.prefetch})", ranks=[0])

    def _put_layer(self, i: int):
        layer = _slice_layer(self._stacked, i)
        return jax.device_put(jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, self.dtype) if np.issubdtype(
                a.dtype, np.floating) else jnp.asarray(a), layer))

    def forward(self, input_ids) -> jnp.ndarray:
        """Full-context logits with layer streaming."""
        ids = jnp.asarray(input_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        x = self._jit_embed(self._small["embed_tokens"],
                            self._small.get("embed_pos"),
                            self._small.get("embed_ln"), ids)
        # pipeline: enqueue next layers' uploads before blocking on compute
        buffers = {}
        for j in range(min(self.prefetch + 1, self.n_layer)):
            buffers[j] = self._put_layer(j)
        for i in range(self.n_layer):
            layer = buffers.pop(i)
            nxt = i + self.prefetch + 1
            if nxt < self.n_layer:
                buffers[nxt] = self._put_layer(nxt)  # async upload
            x = self._jit_block(layer, x)
            del layer  # device buffer freed after the block consumes it
        return self._jit_head(self._small["embed_tokens"],
                              self._small["ln_f"],
                              self._small.get("lm_head"), x)

    __call__ = forward

    def score(self, input_ids) -> np.ndarray:
        """Per-sequence mean log-likelihood (throughput-style batch
        scoring, the ZeRO-Inference serving mode)."""
        ids = jnp.asarray(input_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        logits = self.forward(ids)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        token_ll = jnp.take_along_axis(logp, ids[:, 1:][..., None],
                                       axis=-1)[..., 0]
        return np.asarray(jnp.mean(token_ll, axis=-1))
