from .config import DeepSpeedInferenceConfig
from .engine import InferenceEngine
from .zero_inference import ZeroInferenceEngine

__all__ = ["DeepSpeedInferenceConfig", "InferenceEngine",
           "ZeroInferenceEngine"]
