"""Thread-context inference for graftsync — which thread runs each function?

The PR-11 serving front end split the process into exactly two execution
contexts: the asyncio **event loop** (every coroutine, every loop
callback) and the dedicated **engine step thread** (the
``threading.Thread(target=...)`` body that owns ``ServingEngine.step``).
The whole design rests on the handoffs between them being explicit — the
op queue, ``loop.call_soon_threadsafe``, ``loop.run_in_executor`` — so a
static analyzer can recover the context of every function by seeding the
obvious anchors and propagating along *direct* calls only.

Like the rest of :mod:`deepspeed_tpu.analysis` this is plain :mod:`ast`
over one module: no jax, no threading, no execution.

Seeds
-----
* ``async def``                            -> LOOP (a coroutine body only
  ever runs on the loop thread)
* ``threading.Thread(target=f)``           -> ``f`` is ENGINE
* method ``step`` of ``class ServingEngine`` -> ENGINE
* callbacks handed to ``call_soon_threadsafe`` / ``call_soon`` /
  ``call_later`` / ``add_done_callback``    -> LOOP (asyncio invokes
  them on the loop thread regardless of who scheduled them)
* callables handed to ``<...>bridge.call(f)`` -> ENGINE (the op queue is
  the one sanctioned crossing; the bridge executes ``f`` on the step
  thread)
* callables handed to ``run_in_executor``  -> EXECUTOR (a worker thread:
  exempt from loop-blocking rules, not an engine context)

Propagation
-----------
A caller's contexts flow to every callee it invokes *directly* (bare
name, ``self.method()``, local alias — the same resolution
:class:`~.dataflow.ModuleIndex` uses for trace propagation).  Passing a
function as an argument does **not** propagate: a reference crossing a
queue or a callback API is a handoff, and the seed rules above assign
the receiving side explicitly.  Calling an ``async def`` merely creates
a coroutine object, so propagation never flows *into* coroutines either.
A function reachable from both sides is BOTH and must satisfy the rules
of each.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .dataflow import FunctionNode, FuncInfo, ModuleIndex, node_path

LOOP = "LOOP"
ENGINE = "ENGINE"

#: canonical constructor paths (after import-alias normalisation)
LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "threading.Semaphore", "threading.BoundedSemaphore"}
QUEUE_CTORS = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue"}
THREAD_CTORS = {"threading.Thread"}
CONCURRENT_FUTURE_CTORS = {"concurrent.futures.Future"}

#: event-loop APIs whose callback argument runs on the loop thread;
#: value = positional index of the callback
_LOOP_CALLBACK_APIS = {"call_soon_threadsafe": 0, "call_soon": 0,
                       "call_later": 1, "add_done_callback": 0}

#: engine classes whose ``step`` anchors the step thread
_ENGINE_STEP_CLASSES = {"ServingEngine"}


@dataclass
class ThreadInfo:
    """Inferred execution context(s) of one function."""
    fi: FuncInfo
    contexts: Set[str] = field(default_factory=set)
    seeds: List[str] = field(default_factory=list)
    executor: bool = False

    @property
    def label(self) -> Optional[str]:
        if LOOP in self.contexts and ENGINE in self.contexts:
            return "BOTH"
        if LOOP in self.contexts:
            return LOOP
        if ENGINE in self.contexts:
            return ENGINE
        if self.executor:
            return "EXECUTOR"
        return None


class ThreadContextMap:
    """LOOP / ENGINE / BOTH classification for every function of a module,
    plus the module-wide path sets (locks, queues, threads) the sync
    rules need to recognise guards and handoffs."""

    def __init__(self, index: ModuleIndex):
        self.index = index
        self.infos: Dict[ast.AST, ThreadInfo] = {
            node: ThreadInfo(fi) for node, fi in index.functions.items()}
        #: dotted paths of threading.Lock()/Condition()/... objects
        self.lock_paths: Set[str] = set()
        #: dotted paths of queue.Queue() objects (thread-safe handoff)
        self.queue_paths: Set[str] = set()
        #: dotted paths of threading.Thread() objects (``.join`` blocks)
        self.thread_paths: Set[str] = set()
        #: dotted paths of concurrent.futures.Future() objects (their
        #: ``set_result`` IS thread-safe — exempt from the future rule)
        self.concurrent_future_paths: Set[str] = set()
        self._alias: Dict[str, str] = {}
        self._collect_import_aliases()
        self._collect_infra_paths()
        self._seed()
        self._propagate()

    # ------------------------------------------------------------- build
    def _collect_import_aliases(self) -> None:
        """Map names as written to canonical dotted paths, so
        ``import queue as _queue; _queue.Queue()`` still classifies."""
        for node in ast.walk(self.index.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    if al.asname:
                        self._alias[al.asname] = al.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in ("threading", "queue", "asyncio",
                           "concurrent.futures", "time", "socket"):
                    for al in node.names:
                        self._alias[al.asname or al.name] = \
                            f"{mod}.{al.name}"

    def canonical(self, path: Optional[str]) -> Optional[str]:
        """Rewrite the leading component of ``path`` through the import
        aliases (``_queue.Queue`` -> ``queue.Queue``)."""
        if path is None:
            return None
        if path in self._alias:
            return self._alias[path]
        head, _, rest = path.partition(".")
        if head in self._alias:
            return f"{self._alias[head]}.{rest}" if rest else self._alias[head]
        return path

    def _collect_infra_paths(self) -> None:
        for node in ast.walk(self.index.tree):
            value = None
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, list(node.targets)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            ctor = self.canonical(node_path(value.func))
            if ctor is None:
                continue
            if ctor in LOCK_CTORS:
                dest = self.lock_paths
            elif ctor in QUEUE_CTORS:
                dest = self.queue_paths
            elif ctor in THREAD_CTORS:
                dest = self.thread_paths
            elif ctor in CONCURRENT_FUTURE_CTORS:
                dest = self.concurrent_future_paths
            else:
                continue
            for t in targets:
                p = node_path(t)
                if p is not None:
                    dest.add(p)

    def _seed(self) -> None:
        for node, info in self.infos.items():
            if isinstance(node, ast.AsyncFunctionDef):
                info.contexts.add(LOOP)
                info.seeds.append("async def")
            fi = info.fi
            if isinstance(node, ast.FunctionDef) and \
                    fi.class_name in _ENGINE_STEP_CLASSES and \
                    node.name == "step":
                info.contexts.add(ENGINE)
                info.seeds.append(f"{fi.class_name}.step")

        # call-site seeds need the enclosing scope/class for resolution
        outer = self

        class SeedVisitor(ast.NodeVisitor):
            def __init__(v):
                v.scope: List[FuncInfo] = []
                v.cls: List[str] = []

            def visit_ClassDef(v, node):
                v.cls.append(node.name)
                v.generic_visit(node)
                v.cls.pop()

            def _visit_fn(v, node):
                v.scope.append(outer.index.functions[node])
                v.generic_visit(node)
                v.scope.pop()

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_Lambda(v, node):
                v._visit_fn(node)

            def visit_Call(v, node):
                scope = v.scope[-1] if v.scope else None
                cls = v.cls[-1] if v.cls else None
                outer._seed_call(node, scope, cls)
                v.generic_visit(node)

        SeedVisitor().visit(self.index.tree)

    def _seed_call(self, call: ast.Call, scope: Optional[FuncInfo],
                   cls: Optional[str]) -> None:
        func = call.func
        # threading.Thread(target=f) -> f runs on its own thread: ENGINE
        if self.canonical(node_path(func)) in THREAD_CTORS:
            for kw in call.keywords:
                if kw.arg == "target":
                    self._seed_ref(kw.value, scope, cls, ENGINE,
                                   "threading.Thread target")
            return
        if not isinstance(func, ast.Attribute):
            return
        # loop.call_soon_threadsafe(cb, ...) and friends -> cb is LOOP
        if func.attr in _LOOP_CALLBACK_APIS:
            idx = _LOOP_CALLBACK_APIS[func.attr]
            if len(call.args) > idx:
                self._seed_ref(call.args[idx], scope, cls, LOOP,
                               f"{func.attr} callback")
            return
        # loop.run_in_executor(None, f, ...) -> f runs on a worker thread
        if func.attr == "run_in_executor":
            if len(call.args) > 1:
                fi = self.index._resolve_target(call.args[1], scope, cls)
                if fi is not None:
                    info = self.infos[fi.node]
                    info.executor = True
                    info.seeds.append("run_in_executor target")
            return
        # <...>bridge.call(f) -> the op queue runs f on the step thread
        if func.attr == "call" and call.args:
            recv = node_path(func.value)
            if recv is not None and recv.split(".")[-1].endswith("bridge"):
                self._seed_ref(call.args[0], scope, cls, ENGINE,
                               "bridge.call handoff")

    def _seed_ref(self, expr: ast.expr, scope: Optional[FuncInfo],
                  cls: Optional[str], context: str, why: str) -> None:
        fi = self.index._resolve_target(expr, scope, cls)
        if fi is None:
            return
        # a coroutine handed to a loop API still runs on the loop; an
        # async def can never acquire the ENGINE context
        if context == ENGINE and isinstance(fi.node, ast.AsyncFunctionDef):
            return
        info = self.infos[fi.node]
        if context not in info.contexts:
            info.contexts.add(context)
        info.seeds.append(why)

    def _propagate(self) -> None:
        """Flow each function's contexts to its directly-called callees
        (bare name / ``self.method()`` / local alias) to a fixpoint."""
        by_name_module = {fi.node.name: fi
                          for fi in self.index.functions.values()
                          if fi.parent is None
                          and isinstance(fi.node, FunctionNode)}
        methods: Dict[Tuple[str, str], FuncInfo] = {}
        for fi in self.index.functions.values():
            if fi.class_name and isinstance(fi.node, FunctionNode):
                methods[(fi.class_name, fi.node.name)] = fi

        def callees(fi: FuncInfo) -> List[FuncInfo]:
            out: List[FuncInfo] = []
            aliases: Dict[str, FuncInfo] = {}
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, (ast.Name, ast.Attribute)):
                    cal = self.index._resolve_callee(
                        n.value, fi, aliases, by_name_module, methods)
                    if cal is not None:
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                aliases[t.id] = cal
                if isinstance(n, ast.Call):
                    cal = self.index._resolve_callee(
                        n.func, fi, aliases, by_name_module, methods)
                    if cal is not None:
                        out.append(cal)
            return out

        frontier = [info.fi for info in self.infos.values()
                    if info.contexts]
        while frontier:
            fi = frontier.pop()
            ctxs = self.infos[fi.node].contexts
            for cal in callees(fi):
                if isinstance(cal.node, ast.AsyncFunctionDef):
                    continue    # calling a coroutine fn just builds the object
                tgt = self.infos[cal.node]
                missing = ctxs - tgt.contexts
                if missing:
                    tgt.contexts.update(missing)
                    frontier.append(cal)

    # ----------------------------------------------------------- queries
    def info(self, node: ast.AST) -> Optional[ThreadInfo]:
        return self.infos.get(node)

    def contexts(self, node: ast.AST) -> Set[str]:
        info = self.infos.get(node)
        return set(info.contexts) if info is not None else set()

    def loop_functions(self) -> Iterator[ThreadInfo]:
        """Functions that run on the event loop (including BOTH), minus
        executor targets — the scope of the loop-blocking rules."""
        for info in self.infos.values():
            if LOOP in info.contexts and not info.executor:
                yield info

    def engine_functions(self) -> Iterator[ThreadInfo]:
        """Functions that run on the step thread (including BOTH)."""
        for info in self.infos.values():
            if ENGINE in info.contexts:
                yield info

    def labels(self) -> Dict[str, str]:
        """``qualname -> LOOP|ENGINE|BOTH|EXECUTOR`` for every function
        with an inferred context, deterministic across runs."""
        out: Dict[str, str] = {}
        for node, info in sorted(self.infos.items(),
                                 key=lambda kv: (kv[1].fi.qualname,
                                                 kv[0].lineno)):
            lab = info.label
            if lab is None:
                continue
            key = info.fi.qualname
            if key in out:        # lambdas can share a qualname
                key = f"{key}@{node.lineno}"
            out[key] = lab
        return out


def held_locks_walk(fn_node: ast.AST, lock_paths: Set[str],
                    canonical=None) -> Iterator[Tuple[ast.AST,
                                                      Tuple[str, ...]]]:
    """Yield ``(node, held)`` for every AST node lexically inside
    ``fn_node`` (not descending into nested functions/classes), where
    ``held`` is the tuple of lock paths whose ``with`` blocks enclose
    the node, in acquisition order."""
    canon = canonical or (lambda p: p)

    def rec(node: ast.AST, held: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FunctionNode + (ast.ClassDef, ast.Lambda)):
                continue
            yield child, held
            if isinstance(child, ast.With):
                acquired = list(held)
                for item in child.items:
                    yield from rec(item, tuple(held))
                    p = canon(node_path(item.context_expr))
                    if p in lock_paths:
                        acquired.append(p)
                for s in child.body:
                    yield s, tuple(acquired)
                    yield from rec(s, tuple(acquired))
            else:
                yield from rec(child, held)

    yield from rec(fn_node, ())
