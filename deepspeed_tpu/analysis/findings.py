"""Finding datatypes shared by the graftlint rule engine and CLI.

A :class:`Finding` is one diagnostic at one source location.  Findings
carry a stable *fingerprint* (rule, file, enclosing function, and the
whitespace-normalised source line plus an occurrence counter) so a
baseline file keeps matching after unrelated edits shift line numbers.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Dict

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}
_WS = re.compile(r"\s+")


@dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    func: str = ""
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False
    fingerprint: str = ""

    @property
    def counts_as_error(self) -> bool:
        return (self.severity == ERROR and not self.suppressed
                and not self.baselined)

    def sort_key(self):
        return (self.path, self.line, self.col,
                _SEVERITY_ORDER.get(self.severity, 9), self.rule)

    def format_human(self) -> str:
        tag = {ERROR: "E", WARNING: "W", INFO: "I"}.get(self.severity, "?")
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: [{tag}:{self.rule}] {self.message}"
        if self.func:
            out += f"  (in {self.func})"
        if self.suppressed:
            out += f"  [suppressed: {self.suppress_reason or 'no reason'}]"
        elif self.baselined:
            out += "  [baselined]"
        return out

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "func": self.func,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
            "baselined": self.baselined,
        }


def assign_fingerprints(findings, source_lines) -> None:
    """Stamp stable fingerprints onto ``findings`` (all from one file).

    The key deliberately excludes the line *number*: two findings of the
    same rule on identical source text are disambiguated by an
    occurrence index, so inserting code above a grandfathered finding
    does not invalidate a baseline.
    """
    seen: Dict[str, int] = {}
    for f in sorted(findings, key=lambda x: (x.line, x.col, x.rule)):
        text = ""
        if 1 <= f.line <= len(source_lines):
            text = _WS.sub("", source_lines[f.line - 1])
        key = f"{f.rule}|{f.path}|{f.func}|{text}"
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        digest = hashlib.sha1(f"{key}|{idx}".encode()).hexdigest()[:16]
        f.fingerprint = digest
