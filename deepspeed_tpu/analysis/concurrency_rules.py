"""graftsync rules — each one enforces a PR-11 front-end design rule.

==========================  ================================================
rule id                     design rule it enforces (serving/frontend)
==========================  ================================================
blocking-call-in-coroutine  "``step()`` must never run on the event loop —
                            a single decode dispatch would stall every
                            connection."  Any synchronous sleep / socket /
                            file / queue / join / device-sync call inside
                            LOOP context freezes every open connection for
                            its duration; hand it to a worker via
                            ``loop.run_in_executor`` or await an async
                            equivalent.
cross-thread-engine-access  "``call(fn)`` is the only sanctioned way for
                            the front end to READ engine state."  The
                            engine's dicts are mutated mid-step, so a
                            LOOP-context read or write of
                            ``ServingEngine``/``Scheduler``/``SlotPool``
                            state observes torn updates.
unsafe-future-resolution    "the loop thread delivers" — asyncio futures
                            are not thread-safe; ``set_result`` /
                            ``set_exception`` from the step thread must be
                            marshalled with ``loop.call_soon_threadsafe``
                            (the bridge's ``_resolve``/``_reject`` shape).
await-while-holding-lock    a ``threading.Lock`` held across an ``await``
                            is held for an unbounded number of loop
                            iterations, and the engine thread contending
                            for it stalls the batch; also flags
                            inconsistent lock-acquisition order across
                            functions (AB/BA deadlock).
unguarded-shared-write      every LOOP<->ENGINE handoff goes through the
                            op queue or a lock; an attribute written from
                            both contexts with neither is a data race
                            (torn dict iteration, lost update).
==========================  ================================================

All rules key off :class:`~.concurrency.ThreadContextMap`; a module with
no seeds (no coroutines, no threads) produces no findings, which keeps
the tier silent on the non-concurrent 95% of the codebase.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .concurrency import (ENGINE, LOOP, ThreadContextMap, held_locks_walk)
from .dataflow import FunctionNode, node_path, target_paths
from .findings import ERROR, Finding
from .rules import ModuleContext, Rule

#: dotted-path prefixes that denote engine-owned state from the front
#: end's perspective (ServingEngine / Scheduler / SlotPool live behind
#: these roots in every module of serving/frontend)
_ENGINE_ROOTS = ("self.srv", "self.engine", "self._srv", "self._engine",
                 "srv", "engine")

#: call attributes that hand a callable/reference across the boundary on
#: purpose — their arguments are exempt from cross-thread access checks
_HANDOFF_ATTRS = {"call", "run_in_executor", "call_soon_threadsafe",
                  "call_soon", "call_later", "add_done_callback"}

#: method calls that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "remove", "clear", "pop",
             "popitem", "update", "add", "discard", "setdefault"}


def get_thread_map(ctx: ModuleContext) -> ThreadContextMap:
    m = getattr(ctx, "_thread_map", None)
    if m is None:
        m = ThreadContextMap(ctx.index)
        ctx._thread_map = m
    return m


def _own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Every AST node lexically owned by ``fn_node`` — nested functions,
    lambdas, and classes are skipped (they are analysed under their own
    inferred context, which is what makes executor/bridge-handoff bodies
    naturally exempt here)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, FunctionNode + (ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _is_engine_root(path: Optional[str]) -> bool:
    return path in _ENGINE_ROOTS


class BlockingCallInCoroutineRule(Rule):
    id = "blocking-call-in-coroutine"
    severity = ERROR
    short = ("synchronous blocking call inside event-loop context "
             "(stalls every open connection)")

    _SOCKET_ATTRS = {"recv", "recvfrom", "recv_into", "sendall", "accept"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tmap = get_thread_map(ctx)
        for info in tmap.loop_functions():
            nodes = list(_own_nodes(info.fi.node))
            awaited = {id(n.value) for n in nodes
                       if isinstance(n, ast.Await)
                       and isinstance(n.value, ast.Call)}
            for n in nodes:
                if not isinstance(n, ast.Call) or id(n) in awaited:
                    continue
                msg = self._blocking_reason(tmap, n)
                if msg is not None:
                    yield self.finding(ctx, n, msg, info.fi.qualname)

    def _blocking_reason(self, tmap: ThreadContextMap,
                         call: ast.Call) -> Optional[str]:
        path = tmap.canonical(node_path(call.func))
        if path == "time.sleep":
            return ("time.sleep blocks the event loop — every open "
                    "connection stalls; use `await asyncio.sleep(...)`")
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            return ("synchronous file I/O on the event loop — hand it to "
                    "a worker via `await loop.run_in_executor(...)`")
        if path == "jax.block_until_ready":
            return ("jax.block_until_ready is a device sync — it parks "
                    "the loop for a full dispatch; run it on the step "
                    "thread via `bridge.call`")
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = node_path(call.func.value)
        if attr in self._SOCKET_ATTRS:
            return (f"synchronous socket .{attr}() on the event loop — "
                    "use the asyncio stream APIs (`await reader.read`, "
                    "`await writer.drain`)")
        if attr == "block_until_ready":
            return ("`.block_until_ready()` is a device sync — run it on "
                    "the step thread via `bridge.call`")
        if attr == "step" and recv is not None and \
                recv.split(".")[-1].lstrip("_") in ("srv", "engine"):
            return (f"direct `{recv}.step()` on the event loop — a decode "
                    "dispatch stalls every connection; the step thread "
                    "owns step() (submit work through the bridge)")
        if attr == "join" and recv in tmap.thread_paths:
            return (f"`{recv}.join()` blocks the loop until the thread "
                    "exits — wrap it: `await loop.run_in_executor(None, "
                    f"{recv}.join)`")
        if attr == "get" and recv in tmap.queue_paths:
            for kw in call.keywords:
                if kw.arg == "block" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is False:
                    return None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and call.args[0].value is False:
                return None
            return (f"blocking `{recv}.get()` on the event loop — use "
                    "get_nowait() or an asyncio.Queue on this side of "
                    "the boundary")
        return None


class CrossThreadEngineAccessRule(Rule):
    id = "cross-thread-engine-access"
    severity = ERROR
    short = ("event-loop code touches engine state directly instead of "
             "going through bridge.call")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tmap = get_thread_map(ctx)
        for info in tmap.loop_functions():
            nodes = list(_own_nodes(info.fi.node))
            handoff_args: Set[int] = set()
            for n in nodes:
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _HANDOFF_ATTRS:
                    for arg in list(n.args) + [kw.value
                                               for kw in n.keywords]:
                        handoff_args.update(id(x) for x in ast.walk(arg))
            for n in nodes:
                if not isinstance(n, ast.Attribute) or id(n) in handoff_args:
                    continue
                # flag the first deref step past an engine root — one
                # finding per chain, and the bare reference (a handoff)
                # stays legal
                if not _is_engine_root(node_path(n.value)):
                    continue
                if n.attr == "step":
                    continue   # blocking-call-in-coroutine owns step()
                root = node_path(n.value)
                yield self.finding(
                    ctx, n,
                    f"LOOP-context access to engine state "
                    f"`{root}.{n.attr}` — the engine is single-threaded "
                    "on the step thread and its dicts are mutated "
                    "mid-step; read it via `await bridge.call(lambda "
                    "srv: ...)`", info.fi.qualname)


class UnsafeFutureResolutionRule(Rule):
    id = "unsafe-future-resolution"
    severity = ERROR
    short = ("asyncio future resolved off-loop without "
             "call_soon_threadsafe")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tmap = get_thread_map(ctx)
        for info in tmap.engine_functions():
            conc = set(tmap.concurrent_future_paths)
            conc.update(self._concurrent_params(info.fi.node))
            for n in _own_nodes(info.fi.node):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("set_result", "set_exception")):
                    continue
                recv = node_path(n.func.value)
                if recv is not None and recv in conc:
                    continue   # concurrent.futures.Future IS thread-safe
                yield self.finding(
                    ctx, n,
                    f"`{recv or '<expr>'}.{n.func.attr}()` runs on the "
                    "step thread — asyncio futures are not thread-safe; "
                    "marshal it: `loop.call_soon_threadsafe(...)`",
                    info.fi.qualname)

    @staticmethod
    def _concurrent_params(fn_node: ast.AST) -> Iterator[str]:
        args = getattr(fn_node, "args", None)
        if args is None:
            return
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            if a.annotation is None:
                continue
            try:
                text = ast.unparse(a.annotation)
            except Exception:      # pragma: no cover - malformed annotation
                continue
            if "concurrent" in text:
                yield a.arg


class AwaitWhileHoldingLockRule(Rule):
    id = "await-while-holding-lock"
    severity = ERROR
    short = ("await inside a threading.Lock `with` block, or AB/BA lock "
             "order across functions")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tmap = get_thread_map(ctx)
        if not tmap.lock_paths:
            return
        #: (outer, inner) -> (line, qualname) of first acquisition site
        orders: Dict[Tuple[str, str], Tuple[int, str, ast.AST]] = {}
        for node, fi in ctx.index.functions.items():
            if not isinstance(node, FunctionNode):
                continue
            for sub, held in held_locks_walk(node, tmap.lock_paths,
                                             tmap.canonical):
                if isinstance(sub, ast.Await) and held:
                    yield self.finding(
                        ctx, sub,
                        f"`await` while holding threading lock "
                        f"`{held[-1]}` — the lock stays held across an "
                        "unbounded suspension and the engine thread "
                        "contending for it stalls the batch; release "
                        "before awaiting (or use asyncio.Lock)",
                        fi.qualname)
                if isinstance(sub, ast.With):
                    inner_held = list(held)
                    for item in sub.items:
                        p = tmap.canonical(node_path(item.context_expr))
                        if p not in tmap.lock_paths:
                            continue
                        for outer in inner_held:
                            if outer != p:
                                orders.setdefault(
                                    (outer, p),
                                    (sub.lineno, fi.qualname, sub))
                        inner_held.append(p)
        reported: Set[frozenset] = set()
        for (a, b), (line, qual, node) in sorted(
                orders.items(), key=lambda kv: kv[1][0]):
            rev = orders.get((b, a))
            if rev is None or frozenset((a, b)) in reported:
                continue
            reported.add(frozenset((a, b)))
            later = (line, qual, node) if line >= rev[0] else rev
            other = rev if later is not rev else (line, qual, node)
            yield self.finding(
                ctx, later[2],
                f"inconsistent lock order: `{b if later[0] == line else a}`"
                f" is acquired while holding the other lock here, but "
                f"{other[1]} (line {other[0]}) acquires them in the "
                "opposite order — classic AB/BA deadlock",
                later[1])


class UnguardedSharedWriteRule(Rule):
    id = "unguarded-shared-write"
    severity = ERROR
    short = ("attribute written from both LOOP and ENGINE contexts with "
             "no lock on at least one side")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tmap = get_thread_map(ctx)
        #: (class, attr) -> side -> [(line, qualname, guarded, node)]
        sites: Dict[Tuple[str, str], Dict[str, List]] = {}
        for node, fi in ctx.index.functions.items():
            if not isinstance(node, FunctionNode) or not fi.class_name:
                continue
            ctxs = tmap.contexts(node)
            if not ctxs:
                continue
            for sub, held in held_locks_walk(node, tmap.lock_paths,
                                             tmap.canonical):
                for path in self._written_paths(sub):
                    if not path.startswith("self.") or path.count(".") != 1:
                        continue
                    if path in tmap.queue_paths:
                        continue   # the queue IS the sanctioned handoff
                    attr = path.split(".", 1)[1]
                    rec = sites.setdefault((fi.class_name, attr),
                                           {LOOP: [], ENGINE: []})
                    for side in (LOOP, ENGINE):
                        if side in ctxs:
                            rec[side].append((sub.lineno, fi.qualname,
                                              bool(held), sub))
        for (cls, attr), rec in sorted(sites.items()):
            loop_sites, engine_sites = rec[LOOP], rec[ENGINE]
            if not loop_sites or not engine_sites:
                continue
            unguarded = [s for s in loop_sites + engine_sites if not s[2]]
            if not unguarded:
                continue
            anchor = next((s for s in loop_sites if not s[2]), unguarded[0])
            loop_lines = sorted({s[0] for s in loop_sites})
            eng_lines = sorted({s[0] for s in engine_sites})
            yield self.finding(
                ctx, anchor[3],
                f"`self.{attr}` is written from both LOOP (line "
                f"{', '.join(map(str, loop_lines))}) and ENGINE (line "
                f"{', '.join(map(str, eng_lines))}) contexts without a "
                "lock — serialize one side through the op queue or guard "
                "both sides with one lock", anchor[1])

    @staticmethod
    def _written_paths(node: ast.AST) -> Iterator[str]:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                yield from target_paths(t)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            yield from target_paths(node.target)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                yield from target_paths(t)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            p = node_path(node.func.value)
            if p is not None:
                yield p


SYNC_RULES = (BlockingCallInCoroutineRule(), CrossThreadEngineAccessRule(),
              UnsafeFutureResolutionRule(), AwaitWhileHoldingLockRule(),
              UnguardedSharedWriteRule())

SYNC_RULE_IDS = {r.id for r in SYNC_RULES}
