"""graftcheck's abstract interpreter.

Statically enumerates the reachable abstract signature set of every
watched serving jit (the programs ``ServingEngine._ensure_watch``
wraps) by interpreting the REAL method bodies in
``serving/engine.py``, ``serving/slot_pool.py``,
``serving/paged_pool.py`` and ``inference/engine.py`` over the
:mod:`absdomain` lattice — pure stdlib ``ast``, no jax import.

Three curated tables bound the interpretation (each is a *documented
modelling decision*, kept tiny so drift against the real code is
reviewable):

``DRIVERS``
    The calling contexts.  The serving step loop itself is host
    orchestration full of I/O and bookkeeping, so instead of
    interpreting ``step()`` top-down, each driver seeds one
    step-reachable entry point (`_admit`, `_admit_batch`,
    `_prefill_chunk_step`, `_decode_step`, paged
    ``ensure_writable``) with abstract arguments derived from the
    config env — e.g. a singleton admission's seed length is
    ``IntRange(1, min(prefill_chunk, max_prompt_len))``.  The batched
    driver iterates bucket widths eagerly because the reachable batch
    set depends on the width (token-budget grant semantics).

``SKIP_MODELS``
    Host helpers interpreted as opaque models (``pool.alloc`` returns
    an opaque int, ``metrics.record_*`` return nothing).  Everything
    else on a known class is interpreted from its AST.

``WATCHED_MODELS``
    The abstract return value of each watched jit (a jit body is
    traced code — its *callers* are what decide signatures, so the
    body itself is modelled, not interpreted).

Interpretation is path-sensitive (unresolvable branches fork, capped
at :data:`MAX_PATHS`), loops run body-once except the admission
code's power-of-two doubling loop (``b = K; while b < n: b *= 2``)
which is recognised and collapsed to a :class:`~.absdomain.FiniteSet`
— that recognition is what turns "a prompt length in [1, 256]" into
"a padded width in {16, 32, 64, 128, 256}".

The output contract: :func:`enumerate_signatures` returns exactly the
strings :func:`deepspeed_tpu.telemetry.watchdog.manifest_signature`
renders at runtime, so a manifest diff is meaningful in both
directions.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .absdomain import (COMMITTED, HOST, UNCOMMITTED, AbsValue, Arr, Dim,
                        FiniteSet, IntRange, Known, Obj, Scalar,
                        SignatureError, Tree, Tup, Unbounded, Unknown,
                        dim_of, expand_signatures)
from .findings import ERROR, Finding
from . import shape_rules
from .shape_rules import DTYPE_NAMES, DTypeVal, as_dim, binop

#: fork ceiling per interpreted block — serving methods have a handful
#: of data-dependent guards each; hitting this means the interpreter is
#: lost, and the affected values degrade to Unknown via dead paths
MAX_PATHS = 64
MAX_CALL_DEPTH = 16

#: files the project index loads (relative to the repo root) — the
#: modules that define watched jits or methods reachable from step()
PROJECT_FILES = (
    "deepspeed_tpu/serving/engine.py",
    "deepspeed_tpu/serving/slot_pool.py",
    "deepspeed_tpu/serving/paged_pool.py",
    "deepspeed_tpu/inference/engine.py",
)

_SERVING_ENGINE = "deepspeed_tpu/serving/engine.py"


# ----------------------------------------------------------------------
# extra interp-local values
# ----------------------------------------------------------------------
class DictVal(Tree):
    """A dict literal whose entries we track (still renders ``*``)."""

    __slots__ = ("entries",)

    def __init__(self, entries: Dict[str, AbsValue],
                 placement: str = COMMITTED):
        super().__init__(placement, "dict")
        self.entries = dict(entries)


class ListOf(AbsValue):
    """A homogeneous list/iterator: one element model + abstract length."""

    __slots__ = ("elem", "length", "maybe_empty")

    def __init__(self, elem: AbsValue, length: Optional[Dim] = None,
                 maybe_empty: bool = True):
        self.elem = elem
        self.length = length if length is not None \
            else Unbounded("list length")
        self.maybe_empty = maybe_empty

    def __repr__(self):
        return f"ListOf({self.elem!r})"


class ModuleFn(AbsValue):
    """A dotted module path (``np.zeros``) flowing as a callable."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        self.path = path

    def __repr__(self):
        return f"ModuleFn({self.path})"


# ----------------------------------------------------------------------
# project index
# ----------------------------------------------------------------------
class ModuleInfo:
    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.aliases: Dict[str, str] = {}       # local name -> canonical
        self.constants: Dict[str, AbsValue] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.bases: Dict[str, List[str]] = {}
        self._scan()

    def _scan(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    self.aliases[name] = _canon_module(a.name)
            elif isinstance(node, ast.ImportFrom):
                mod = _canon_module(node.module or "")
                for a in node.names:
                    name = a.asname or a.name
                    self.aliases[name] = f"{mod}.{a.name}" if mod \
                        else a.name
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                val = _literal_value(node.value)
                if val is not None:
                    self.constants[node.targets[0].id] = val
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                self.bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(node.name, item.name)] = item


def _canon_module(name: str) -> str:
    if name in ("numpy",):
        return "np"
    if name in ("jax.numpy",):
        return "jnp"
    return name


def _literal_value(node: ast.expr) -> Optional[AbsValue]:
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, (int, float, str, bool, type(None))):
        return Scalar(node.value)
    if isinstance(node, (ast.Tuple, ast.List)):
        items = [_literal_value(e) for e in node.elts]
        if all(i is not None for i in items):
            return Tup(items)  # type: ignore[arg-type]
    return None


class ProjectIndex:
    """Parsed serving modules + the watched-jit name lists, read from
    the real ``serving/engine.py`` AST so the checker can never drift
    from what the watchdog actually wraps."""

    def __init__(self, root: str):
        self.root = root
        self.modules: List[ModuleInfo] = []
        for rel in PROJECT_FILES:
            path = os.path.join(root, rel)
            if not os.path.isfile(path):
                continue
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            self.modules.append(
                ModuleInfo(path, rel, ast.parse(src, filename=path)))
        self.watched: Dict[str, Tuple[str, ...]] = {}
        for mod in self.modules:
            if mod.rel.endswith("serving/engine.py"):
                for key in ("_WATCHED_ENGINE_JITS", "_WATCHED_POOL_JITS",
                            "_WATCHED_SERVING_JITS",
                            "_WATCHED_DRAFTER_JITS"):
                    v = mod.constants.get(key)
                    if isinstance(v, Tup):
                        self.watched[key] = tuple(
                            s.value for s in v.items
                            if isinstance(s, Scalar))

    def resolve_method(self, kind: str, name: str
                       ) -> Optional[Tuple[ast.FunctionDef, ModuleInfo]]:
        for cls in self._mro(kind):
            for mod in self.modules:
                fn = mod.methods.get((cls, name))
                if fn is not None:
                    return fn, mod
        return None

    def _mro(self, kind: str) -> List[str]:
        out, frontier = [], [kind]
        while frontier:
            cls = frontier.pop(0)
            if cls in out:
                continue
            out.append(cls)
            for mod in self.modules:
                frontier.extend(mod.bases.get(cls, []))
        return out


# ----------------------------------------------------------------------
# watched-call return models
# ----------------------------------------------------------------------
def _logits(batch: Any, env: dict) -> Arr:
    return Arr((batch, Known(1), Known(int(env["vocab_size"]))),
               env.get("logits_dtype", "float32"), COMMITTED)


def _batch_of(a: AbsValue) -> Any:
    return a.shape[0] if isinstance(a, Arr) and a.ndim else Known(1)


WATCHED_MODELS = {
    "_jit_prefill_at": lambda args, kw, env: Tup(
        [_logits(_batch_of(args[1]), env), Tree(COMMITTED, "pre_cache")]),
    "_jit_decode": lambda args, kw, env: Tup(
        [_logits(_batch_of(args[2]), env), Tree(COMMITTED, "cache")]),
    "_jit_prefill_chunk": lambda args, kw, env: Tup(
        [_logits(Known(1), env), Tree(COMMITTED, "cache")]),
    "_jit_sample": lambda args, kw, env: Arr(
        (_batch_of(args[0]),), "int32", COMMITTED),
    "_jit_verify_k": lambda args, kw, env: Tup(
        [Tree(COMMITTED, "cache"),
         Arr((_batch_of(args[2]),
              args[2].shape[1] if isinstance(args[2], Arr)
              and args[2].ndim > 1 else Known(1)), "int32", COMMITTED),
         Arr((_batch_of(args[2]),), "int32", COMMITTED)]),
    "_jit_decode_scan": lambda args, kw, env: Unknown("decode_scan"),
    "_admit_jit": lambda args, kw, env: Tree(COMMITTED, "pool"),
    "_admit_rows_jit": lambda args, kw, env: Tree(COMMITTED, "pool"),
    "_jit_copy_page": lambda args, kw, env: Tree(COMMITTED, "pool"),
    "_jit_gather_pages": lambda args, kw, env: Tree(COMMITTED, "pool"),
    "_jit_scatter_pages": lambda args, kw, env: Tree(COMMITTED, "pool"),
    "_paged_decode_jit": lambda args, kw, env: Tup(
        [_logits(_batch_of(args[2]), env), Tree(COMMITTED, "pool")]),
    "_paged_chunk_jit": lambda args, kw, env: Tup(
        [_logits(Known(1), env), Tree(COMMITTED, "pool")]),
    "_paged_verify_jit": lambda args, kw, env: Tup(
        [Tree(COMMITTED, "pool"),
         Arr((_batch_of(args[2]),
              args[2].shape[1] if isinstance(args[2], Arr)
              and args[2].ndim > 1 else Known(1)), "int32", COMMITTED),
         Arr((_batch_of(args[2]),), "int32", COMMITTED)]),
    "_jit_finite": lambda args, kw, env: Arr(
        (_batch_of(args[0]),), "bool", COMMITTED),
    # fused paged-attention kernel arms: same caller-visible contract as
    # the dense compositions they replace
    "_paged_decode_kernel_jit": lambda args, kw, env: Tup(
        [_logits(_batch_of(args[2]), env), Tree(COMMITTED, "pool")]),
    "_paged_verify_kernel_jit": lambda args, kw, env: Tup(
        [Tree(COMMITTED, "pool"),
         Arr((_batch_of(args[2]),
              args[2].shape[1] if isinstance(args[2], Arr)
              and args[2].ndim > 1 else Known(1)), "int32", COMMITTED),
         Arr((_batch_of(args[2]),), "int32", COMMITTED)]),
    # device current-token twin plumbing: scatter returns the (S,) twin
    # it was handed; spec-cur collapses a (S, K+1) verify output to (S,)
    "_jit_cur_scatter": lambda args, kw, env: args[0]
    if isinstance(args[0], Arr)
    else Arr((Known(int(env["num_slots"])),), "int32", COMMITTED),
    "_jit_spec_cur": lambda args, kw, env: Arr(
        (_batch_of(args[0]),), "int32", COMMITTED),
    "_argmax": lambda args, kw, env: Arr((), "int32", COMMITTED),
}

#: host helpers modelled instead of interpreted: pure bookkeeping, or
#: allocators whose result is an opaque host int (never a dimension)
SKIP_MODELS = {
    ("ServingEngine", "_now"): lambda s, a, kw: Scalar(
        Unbounded("wallclock")),
    ("ServingEngine", "_running_count"): lambda s, a, kw: Scalar(
        Unbounded("live count")),
    ("ServingEngine", "_maybe_retire"): lambda s, a, kw: Scalar(None),
    ("ServingEngine", "_ensure_pages"): lambda s, a, kw: Scalar(None),
    ("ServingEngine", "_ensure_decode_pages"): lambda s, a, kw: Scalar(None),
    ("ServingEngine", "_prefix_plan"): lambda s, a, kw: Scalar(
        Unbounded("prefix plan")),
    ("SlotPool", "alloc"): lambda s, a, kw: Scalar(Unbounded("slot id")),
    ("SlotPool", "release"): lambda s, a, kw: Scalar(None),
    ("SlotPool", "reset_row"): lambda s, a, kw: Scalar(None),
    ("PagedKVPool", "alloc_page"): lambda s, a, kw: Scalar(
        Unbounded("page id")),
    ("PagedKVPool", "ref_page"): lambda s, a, kw: Scalar(None),
    ("PagedKVPool", "unref_page"): lambda s, a, kw: Scalar(None),
    ("PagedKVPool", "_sync_table"): lambda s, a, kw: Scalar(None),
    # the wire hop of a cross-pool transfer: device_put of every block
    # leaf onto the pool's committed placement — the scatter's block
    # operand is COMMITTED by construction, which is the whole point
    ("PagedKVPool", "_land_block"): lambda s, a, kw: Tree(COMMITTED,
                                                          "pool"),
    ("PagedKVPool", "bind_engine"): lambda s, a, kw: Scalar(None),
    ("PagedKVPool", "cache_prefix"): lambda s, a, kw: Scalar(
        Unbounded("cached pages")),
    ("Drafter", "propose"): lambda s, a, kw: Unknown("drafter"),
}


# ----------------------------------------------------------------------
# outcomes / frames
# ----------------------------------------------------------------------
class Outcome:
    __slots__ = ("kind", "value", "frame")

    def __init__(self, kind: str, value: Optional[AbsValue],
                 frame: "Frame"):
        self.kind = kind          # fall | return | raise | break | continue
        self.value = value
        self.frame = frame


class Frame:
    __slots__ = ("locals", "module")

    def __init__(self, locs: Dict[str, AbsValue], module: ModuleInfo):
        self.locals = locs
        self.module = module

    def copy(self) -> "Frame":
        return Frame(dict(self.locals), self.module)


class Record:
    """One watched call site observed during interpretation."""

    __slots__ = ("program", "args", "kwargs", "rel", "line")

    def __init__(self, program: str, args: List[AbsValue],
                 kwargs: Dict[str, AbsValue], rel: str, line: int):
        self.program = program
        self.args = args
        self.kwargs = kwargs
        self.rel = rel
        self.line = line


# ----------------------------------------------------------------------
# the interpreter
# ----------------------------------------------------------------------
class Interp:
    def __init__(self, project: ProjectIndex, env: dict):
        self.project = project
        self.env = env
        self.records: List[Record] = []
        self.findings: List[Finding] = []
        self._depth = 0
        eng = project.watched.get("_WATCHED_ENGINE_JITS", ())
        pool = project.watched.get("_WATCHED_POOL_JITS", ())
        srv = project.watched.get("_WATCHED_SERVING_JITS", ())
        drf = project.watched.get("_WATCHED_DRAFTER_JITS", ())
        self._watched_by_kind = {
            "InferenceEngine": (eng, "InferenceEngine"),
            "SlotPool": (pool, "SlotPool"),
            "PagedKVPool": (pool, "SlotPool"),   # watchdog names both
            #                                      families "SlotPool.*"
            "ServingEngine": (srv, "ServingEngine"),
            "Drafter": (drf, "Drafter"),
        }

    # ------------------------------------------------------------ calls
    def call_method(self, recv: Obj, name: str, args: List[AbsValue],
                    kwargs: Dict[str, AbsValue],
                    call_node: Optional[ast.Call] = None,
                    module: Optional[ModuleInfo] = None) -> AbsValue:
        watched, prefix = self._watched_by_kind.get(recv.kind, ((), ""))
        if name in watched:
            if call_node is not None and module is not None:
                self.records.append(Record(
                    f"{prefix}.{name}", args, kwargs, module.rel,
                    call_node.lineno))
            model = WATCHED_MODELS.get(name)
            return model(args, kwargs, self.env) if model \
                else Unknown(f"watched {name}")
        for cls in self.project._mro(recv.kind):
            skip = SKIP_MODELS.get((cls, name))
            if skip is not None:
                return skip(recv, args, kwargs)
        resolved = self.project.resolve_method(recv.kind, name)
        if resolved is None:
            return Unknown(f"unmodelled method {recv.kind}.{name}")
        fn, mod = resolved
        return self.call_function(fn, mod, recv, args, kwargs)

    def call_function(self, fn: ast.FunctionDef, module: ModuleInfo,
                      recv: Optional[Obj], args: List[AbsValue],
                      kwargs: Dict[str, AbsValue]) -> AbsValue:
        if self._depth >= MAX_CALL_DEPTH:
            return Unknown(f"call depth limit at {fn.name}")
        params = [a.arg for a in fn.args.args]
        bound: Dict[str, AbsValue] = {}
        pos = list(args)
        if params and params[0] in ("self", "cls") and recv is not None:
            bound[params[0]] = recv
            params = params[1:]
        defaults = fn.args.defaults
        # align defaults to the trailing params
        dmap: Dict[str, AbsValue] = {}
        for p, d in zip(params[len(params) - len(defaults):], defaults):
            lit = _literal_value(d)
            dmap[p] = lit if lit is not None else Unknown("default")
        for i, p in enumerate(params):
            if i < len(pos):
                bound[p] = pos[i]
            elif p in kwargs:
                bound[p] = kwargs[p]
            elif p in dmap:
                bound[p] = dmap[p]
            else:
                bound[p] = Unknown(f"unbound param {p}")
        self._depth += 1
        try:
            outs = self.exec_block(fn.body, Frame(bound, module))
        finally:
            self._depth -= 1
        returns = [o.value for o in outs if o.kind == "return"
                   and o.value is not None]
        return _join(returns)

    # ---------------------------------------------------------- stmts
    def exec_block(self, stmts: Sequence[ast.stmt],
                   frame: Frame) -> List[Outcome]:
        states = [frame]
        done: List[Outcome] = []
        for stmt in stmts:
            nxt: List[Frame] = []
            for fr in states:
                for out in self.exec_stmt(stmt, fr):
                    if out.kind == "fall":
                        nxt.append(out.frame)
                    elif out.kind == "raise":
                        pass                       # path dies
                    else:
                        done.append(out)
            states = nxt[:MAX_PATHS]
            if not states:
                break
        done.extend(Outcome("fall", None, fr) for fr in states)
        return done

    def exec_stmt(self, stmt: ast.stmt, frame: Frame) -> List[Outcome]:
        if isinstance(stmt, ast.Return):
            v = self.eval(stmt.value, frame) if stmt.value is not None \
                else Scalar(None)
            return [Outcome("return", v, frame)]
        if isinstance(stmt, ast.Raise):
            return [Outcome("raise", None, frame)]
        if isinstance(stmt, (ast.Break,)):
            return [Outcome("break", None, frame)]
        if isinstance(stmt, (ast.Continue,)):
            return [Outcome("continue", None, frame)]
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value, frame)
            return [Outcome("fall", None, frame)]
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(stmt, frame)
            return [Outcome("fall", None, frame)]
        if isinstance(stmt, ast.If):
            return self._exec_if(stmt, frame)
        if isinstance(stmt, ast.While):
            return self._exec_while(stmt, frame)
        if isinstance(stmt, ast.For):
            return self._exec_for(stmt, frame)
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                v = self.eval(item.context_expr, frame)
                if item.optional_vars is not None:
                    self._store(item.optional_vars, v, frame)
            return self.exec_block(stmt.body, frame)
        if isinstance(stmt, ast.Try):
            # try-body only: handlers model error recovery (rollbacks /
            # re-raises), which never reaches a watched call with a NEW
            # shape — the shapes were built before the dispatch failed
            return self.exec_block(stmt.body, frame)
        if isinstance(stmt, (ast.Pass, ast.Assert, ast.Delete,
                             ast.Import, ast.ImportFrom, ast.Global,
                             ast.Nonlocal, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return [Outcome("fall", None, frame)]
        return [Outcome("fall", None, frame)]

    def _exec_if(self, stmt: ast.If, frame: Frame) -> List[Outcome]:
        t = self.truth(self.eval(stmt.test, frame))
        if t is True:
            return self.exec_block(stmt.body, frame)
        if t is False:
            return self.exec_block(stmt.orelse, frame)
        outs = self.exec_block(stmt.body, frame.copy())
        outs += self.exec_block(stmt.orelse, frame.copy())
        return outs

    def _exec_while(self, stmt: ast.While, frame: Frame) -> List[Outcome]:
        collapsed = self._pow2_while(stmt, frame)
        if collapsed:
            return [Outcome("fall", None, frame)]
        t = self.truth(self.eval(stmt.test, frame))
        if t is False:
            return self.exec_block(stmt.orelse, frame)
        # body-once over-approximation (documented): serving loops do
        # host bookkeeping; shape-relevant values are loop-invariant
        outs = self.exec_block(stmt.body, frame)
        return [Outcome("fall", None, o.frame) if o.kind in
                ("break", "continue") else o for o in outs]

    def _pow2_while(self, stmt: ast.While, frame: Frame) -> bool:
        """Recognise ``while b < n: b *= 2`` over a Known start and a
        bounded n, collapsing to the power-of-two FiniteSet the loop
        can produce.  This is THE abstraction that makes admission
        bucket sets finite."""
        test = stmt.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Lt)
                and isinstance(test.left, ast.Name)
                and len(stmt.body) == 1
                and isinstance(stmt.body[0], ast.AugAssign)
                and isinstance(stmt.body[0].op, ast.Mult)
                and isinstance(stmt.body[0].target, ast.Name)
                and stmt.body[0].target.id == test.left.id
                and isinstance(stmt.body[0].value, ast.Constant)
                and stmt.body[0].value.value == 2):
            return False
        var = test.left.id
        b = frame.locals.get(var)
        n = self.eval(test.comparators[0], frame)
        b_dim = as_dim(b) if b is not None else None
        n_dim = as_dim(n) if n is not None else None
        if not isinstance(b_dim, Known):
            return False
        k0 = b_dim.v
        if isinstance(n_dim, Known):
            v = k0
            while v < n_dim.v:
                v *= 2
            frame.locals[var] = Scalar(v)
            return True
        if isinstance(n_dim, IntRange):
            lo, hi = n_dim.lo, n_dim.hi
        elif n_dim is not None and n_dim.values() is not None:
            lo, hi = min(n_dim.values()), max(n_dim.values())
        else:
            frame.locals[var] = Scalar(Unbounded(
                f"pow2 doubling of {var} over an unbounded count"))
            return True
        out = set()
        v = k0
        while True:
            # v is reachable if some n in [lo, hi] power-of-two-ceils
            # to it: n <= k0 lands on k0; otherwise n in (v/2, v]
            if (v == k0 and lo <= k0) or (v > k0 and lo <= v
                                          and hi > v // 2):
                out.add(v)
            if v >= hi:
                break
            v *= 2
        frame.locals[var] = Scalar(FiniteSet(sorted(out), name=var))
        return True

    def _exec_for(self, stmt: ast.For, frame: Frame) -> List[Outcome]:
        it = self.eval(stmt.iter, frame)
        items: Optional[List[AbsValue]] = None
        if isinstance(it, Tup) and len(it.items) <= MAX_PATHS:
            items = list(it.items)
        if items is not None:
            states = [frame]
            done: List[Outcome] = []
            for item in items:
                nxt = []
                for fr in states:
                    self._store(stmt.target, item, fr)
                    for o in self.exec_block(stmt.body, fr):
                        if o.kind in ("fall", "continue"):
                            nxt.append(o.frame)
                        elif o.kind == "break":
                            done.append(Outcome("fall", None, o.frame))
                        elif o.kind == "return":
                            done.append(o)
                states = nxt[:MAX_PATHS]
            done.extend(Outcome("fall", None, fr) for fr in states)
            return done
        elem = _elem_of(it)
        self._store(stmt.target, elem, frame)
        outs = self.exec_block(stmt.body, frame)
        outs = [Outcome("fall", None, o.frame) if o.kind in
                ("break", "continue") else o for o in outs]
        if not any(o.kind == "fall" for o in outs):
            # every body path broke/returned/raised; the zero-iteration
            # case still falls through
            outs.append(Outcome("fall", None, frame))
        return outs

    # -------------------------------------------------------- assigns
    def _assign(self, stmt: ast.stmt, frame: Frame) -> None:
        if isinstance(stmt, ast.Assign):
            v = self.eval(stmt.value, frame)
            for t in stmt.targets:
                self._store(t, v, frame)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._store(stmt.target, self.eval(stmt.value, frame),
                            frame)
        elif isinstance(stmt, ast.AugAssign):
            cur = self.eval(_load_of(stmt.target), frame)
            v = self._binop(cur, stmt.op, self.eval(stmt.value, frame))
            self._store(stmt.target, v, frame)

    def _store(self, target: ast.expr, value: AbsValue,
               frame: Frame) -> None:
        if isinstance(target, ast.Name):
            frame.locals[target.id] = value
        elif isinstance(target, ast.Attribute):
            recv = self.eval(target.value, frame)
            if isinstance(recv, Obj):
                recv.attrs[target.attr] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            vals = _unpack(value, len(target.elts))
            for t, v in zip(target.elts, vals):
                self._store(t, v, frame)
        elif isinstance(target, ast.Subscript):
            recv = self.eval(target.value, frame)
            if isinstance(recv, DictVal):
                idx = self.eval(target.slice, frame)
                if isinstance(idx, Scalar) and isinstance(idx.value, str):
                    recv.entries[idx.value] = value
            # element stores on arrays never change a shape: ignore

    # ----------------------------------------------------------- exprs
    def eval(self, node: ast.expr, frame: Frame) -> AbsValue:
        if isinstance(node, ast.Constant):
            return Scalar(node.value)
        if isinstance(node, ast.Name):
            return self._load_name(node.id, frame)
        if isinstance(node, ast.Attribute):
            return self._load_attr(node, frame)
        if isinstance(node, ast.Call):
            return self._call(node, frame)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, frame)
        if isinstance(node, ast.BinOp):
            return self._binop(self.eval(node.left, frame), node.op,
                               self.eval(node.right, frame))
        if isinstance(node, ast.UnaryOp):
            return self._unary(node, frame)
        if isinstance(node, ast.Compare):
            return self._compare(node, frame)
        if isinstance(node, ast.BoolOp):
            return self._boolop(node, frame)
        if isinstance(node, (ast.Tuple, ast.List)):
            return Tup([self.eval(e, frame) for e in node.elts])
        if isinstance(node, ast.Dict):
            entries = {}
            placement = COMMITTED
            for k, v in zip(node.keys, node.values):
                val = self.eval(v, frame)
                if k is not None and isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    entries[k.value] = val
            return DictVal(entries, placement)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._comp(node, frame)
        if isinstance(node, ast.IfExp):
            t = self.truth(self.eval(node.test, frame))
            if t is True:
                return self.eval(node.body, frame)
            if t is False:
                return self.eval(node.orelse, frame)
            return _join([self.eval(node.body, frame),
                          self.eval(node.orelse, frame)])
        if isinstance(node, ast.JoinedStr):
            return Scalar(Unbounded("f-string"))
        if isinstance(node, ast.Slice):
            return Unknown("bare slice")
        if isinstance(node, ast.Starred):
            return self.eval(node.value, frame)
        return Unknown(type(node).__name__)

    def _load_name(self, name: str, frame: Frame) -> AbsValue:
        if name in frame.locals:
            return frame.locals[name]
        mod = frame.module
        if name in mod.constants:
            return mod.constants[name]
        if name in mod.aliases:
            return ModuleFn(mod.aliases[name])
        if name in mod.classes:
            return ModuleFn(name)
        return Unknown(f"unbound name {name}")

    def _load_attr(self, node: ast.Attribute, frame: Frame) -> AbsValue:
        recv = self.eval(node.value, frame)
        attr = node.attr
        if isinstance(recv, ModuleFn):
            path = f"{recv.path}.{attr}"
            root = recv.path.split(".", 1)[0]
            if root in ("np", "jnp") and attr in DTYPE_NAMES:
                return DTypeVal(attr)
            if path == "np.newaxis":
                return Scalar(None)
            return ModuleFn(path)
        if isinstance(recv, Obj):
            if attr in recv.attrs:
                return recv.attrs[attr]
            return Unknown(f"unmodelled attr {recv.kind}.{attr}")
        if isinstance(recv, Arr):
            if attr == "shape":
                return Tup([Scalar(d) for d in recv.shape])
            if attr == "ndim":
                return Scalar(recv.ndim)
            if attr == "dtype":
                return DTypeVal(recv.dtype)
            if attr == "size":
                return Scalar(Unbounded("size"))
            if attr == "T" and recv.ndim == 2:
                return Arr((recv.shape[1], recv.shape[0]), recv.dtype,
                           recv.placement)
        return Unknown(f"attr .{attr} on {type(recv).__name__}")

    # ------------------------------------------------------------ calls
    def _call(self, node: ast.Call, frame: Frame) -> AbsValue:
        kwargs = {kw.arg: self.eval(kw.value, frame)
                  for kw in node.keywords if kw.arg is not None}
        args = [self.eval(a, frame) for a in node.args
                if not isinstance(a, ast.Starred)]
        f = node.func
        if isinstance(f, ast.Attribute):
            recv = self.eval(f.value, frame)
            if isinstance(recv, ModuleFn):
                path = f"{recv.path}.{f.attr}"
                rule = shape_rules.RULES.get(path) or _EXTRA_RULES.get(path)
                if rule is not None:
                    return rule(args, kwargs)
                return Unknown(f"no shape rule for {path}")
            if isinstance(recv, Obj):
                return self.call_method(recv, f.attr, args, kwargs,
                                        call_node=node,
                                        module=frame.module)
            if isinstance(recv, ListOf):
                if f.attr == "append":
                    return Scalar(None)
                if f.attr == "pop":
                    return recv.elem
                return Unknown(f"list .{f.attr}")
            if isinstance(recv, (Arr, Tree)):
                return shape_rules.method_call(recv, f.attr, args, kwargs)
            if isinstance(recv, Scalar) and isinstance(recv.value, str):
                return Scalar(Unbounded("str method"))
            return Unknown(f"method on {type(recv).__name__}")
        if isinstance(f, ast.Name):
            fv = self._load_name(f.id, frame)
            if isinstance(fv, ModuleFn):
                rule = shape_rules.RULES.get(fv.path) \
                    or _EXTRA_RULES.get(fv.path)
                if rule is not None:
                    return rule(args, kwargs)
            return self._builtin(f.id, args, kwargs, node, frame)
        return Unknown("indirect call")

    def _builtin(self, name: str, args: List[AbsValue],
                 kwargs: Dict[str, AbsValue], node: ast.Call,
                 frame: Frame) -> AbsValue:
        if name == "len" and args:
            return _length(args[0])
        if name in ("int", "float", "bool") and args:
            return _cast(name, args[0])
        if name in ("min", "max") and args:
            return _minmax(name, args)
        if name == "range":
            return _range(args)
        if name == "enumerate" and args:
            src = args[0]
            if isinstance(src, Tup):
                return Tup([Tup([Scalar(i), v])
                            for i, v in enumerate(src.items)])
            return ListOf(Tup([Scalar(Unbounded("index")),
                               _elem_of(src)]))
        if name == "zip":
            return ListOf(Tup([_elem_of(a) for a in args]))
        if name == "sorted" and args:
            return args[0] if isinstance(args[0], (Tup, ListOf)) \
                else ListOf(_elem_of(args[0]))
        if name == "list" and args:
            return args[0] if isinstance(args[0], (Tup, ListOf)) \
                else ListOf(_elem_of(args[0]))
        if name == "list":
            return ListOf(Unknown("empty"), Known(0))
        if name == "dict" and args:
            src = args[0]
            if isinstance(src, DictVal):
                return DictVal(dict(src.entries), src.placement)
            if isinstance(src, Tree):
                return Tree(src.placement, src.label)
            return Unknown("dict()")
        if name == "dict":
            return DictVal({})
        if name in ("str", "repr"):
            return Scalar(Unbounded("string"))
        if name == "sum" and args:
            return Scalar(Unbounded("sum"))
        if name == "isinstance":
            return Unknown("isinstance")
        if name == "getattr" and len(args) >= 2:
            if isinstance(args[0], Obj) and isinstance(args[1], Scalar) \
                    and isinstance(args[1].value, str):
                if args[1].value in args[0].attrs:
                    return args[0].attrs[args[1].value]
                if len(args) == 3:
                    return args[2]
            return Unknown("getattr")
        if name == "print":
            return Scalar(None)
        return Unknown(f"builtin {name}")

    # ------------------------------------------------------- subscripts
    def _subscript(self, node: ast.Subscript, frame: Frame) -> AbsValue:
        recv = self.eval(node.value, frame)
        if isinstance(recv, DictVal):
            idx = self.eval(node.slice, frame)
            if isinstance(idx, Scalar) and isinstance(idx.value, str):
                return recv.entries.get(
                    idx.value, Tree(recv.placement, idx.value))
            return Tree(recv.placement)
        if isinstance(recv, Tree):
            return Tree(recv.placement, recv.label)
        if isinstance(recv, Tup):
            idx = self.eval(node.slice, frame)
            if isinstance(idx, Scalar) and isinstance(idx.value, int) \
                    and not isinstance(idx.value, bool):
                i = idx.value
                if -len(recv.items) <= i < len(recv.items):
                    return recv.items[i]
            return Unknown("tuple index")
        if isinstance(recv, ListOf):
            return recv.elem
        if isinstance(recv, Arr):
            return self._index_arr(recv, node.slice, frame)
        return Unknown(f"subscript on {type(recv).__name__}")

    def _index_arr(self, arr: Arr, sl: ast.expr, frame: Frame) -> AbsValue:
        parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        shape: List[Dim] = []
        axis = 0
        for part in parts:
            if isinstance(part, ast.Slice):
                if axis >= arr.ndim:
                    return Unknown("over-indexed")
                shape.append(self._slice_dim(arr.shape[axis], part, frame))
                axis += 1
                continue
            v = self.eval(part, frame)
            if isinstance(v, Scalar) and v.value is None:
                shape.append(Known(1))               # newaxis
                continue
            if axis >= arr.ndim:
                return Unknown("over-indexed")
            if isinstance(v, Arr):
                if v.dtype.startswith("bool"):
                    shape.append(Unbounded("boolean mask"))
                else:
                    shape.extend(v.shape)            # advanced indexing
                axis += 1
                continue
            # any scalar-ish index drops the axis
            axis += 1
        shape.extend(arr.shape[axis:])
        return Arr(shape, arr.dtype, arr.placement)

    def _slice_dim(self, dim: Dim, sl: ast.Slice, frame: Frame) -> Dim:
        lo = self.eval(sl.lower, frame) if sl.lower is not None else None
        hi = self.eval(sl.upper, frame) if sl.upper is not None else None
        if sl.step is not None:
            return Unbounded("strided slice")
        lo_d = as_dim(lo) if lo is not None else Known(0)
        if hi is None:
            hi_d: Optional[Dim] = dim
        else:
            hi_d = as_dim(hi)
        if isinstance(lo_d, Known) and isinstance(hi_d, Known):
            return Known(max(0, hi_d.v - lo_d.v))
        if isinstance(lo_d, Known) and lo_d.v == 0 and hi_d is dim:
            return dim
        return Unbounded("abstract slice bounds")

    # ------------------------------------------------------- operators
    def _binop(self, left: AbsValue, op: ast.operator,
               right: AbsValue) -> AbsValue:
        if isinstance(left, (Arr,)) or isinstance(right, (Arr,)):
            return binop(left, right)
        ld = as_dim(left) if isinstance(left, Scalar) else None
        rd = as_dim(right) if isinstance(right, Scalar) else None
        if ld is not None and rd is not None:
            d = _dim_arith(ld, op, rd)
            if d is not None:
                return Scalar(d)
        if isinstance(left, Scalar) and isinstance(right, Scalar) and \
                isinstance(left.value, (int, float)) and \
                isinstance(right.value, (int, float)):
            try:
                return Scalar(_py_arith(left.value, op, right.value))
            except (ZeroDivisionError, TypeError):
                return Unknown("arith error")
        return Unknown("binop")

    def _unary(self, node: ast.UnaryOp, frame: Frame) -> AbsValue:
        v = self.eval(node.operand, frame)
        if isinstance(node.op, ast.Not):
            t = self.truth(v)
            return Scalar(not t) if t is not None else Unknown("not")
        if isinstance(node.op, ast.USub):
            if isinstance(v, Scalar) and isinstance(v.value, (int, float)) \
                    and not isinstance(v.value, bool):
                return Scalar(-v.value)
            return Unknown("negation")
        return Unknown("unary")

    def _compare(self, node: ast.Compare, frame: Frame) -> AbsValue:
        left = self.eval(node.left, frame)
        result: Optional[bool] = None
        for op, comp in zip(node.ops, node.comparators):
            right = self.eval(comp, frame)
            r = _compare_one(left, op, right)
            if r is None:
                return Unknown("compare")
            result = r if result is None else (result and r)
            if result is False:
                return Scalar(False)
            left = right
        return Scalar(bool(result))

    def _boolop(self, node: ast.BoolOp, frame: Frame) -> AbsValue:
        is_and = isinstance(node.op, ast.And)
        last: AbsValue = Scalar(is_and)
        for v in node.values:
            val = self.eval(v, frame)
            t = self.truth(val)
            if t is None:
                return Unknown("boolop")
            if is_and and not t:
                return val
            if not is_and and t:
                return val
            last = val
        return last

    def _comp(self, node, frame: Frame) -> AbsValue:
        gen = node.generators[0]
        it = self.eval(gen.iter, frame)
        inner = frame.copy()
        self._store(gen.target, _elem_of(it), inner)
        elem = self.eval(node.elt, inner)
        return ListOf(elem, maybe_empty=True)

    # ------------------------------------------------------------ truth
    def truth(self, v: AbsValue) -> Optional[bool]:
        if isinstance(v, Scalar):
            if isinstance(v.value, Dim):
                vals = v.value.values()
                if vals is not None:
                    if all(x for x in vals):
                        return True
                    if not any(x for x in vals):
                        return False
                return None
            if isinstance(v.value, (bool, int, float, str, type(None))):
                return bool(v.value)
            return None
        if isinstance(v, Obj):
            return True
        if isinstance(v, (DictVal,)):
            return bool(v.entries) or None
        if isinstance(v, Tree):
            return True
        if isinstance(v, Tup):
            return len(v.items) > 0
        if isinstance(v, ListOf):
            return None if v.maybe_empty else True
        return None


# ----------------------------------------------------------------------
# small value helpers
# ----------------------------------------------------------------------
def _join(vals: List[AbsValue]) -> AbsValue:
    """Pick the most informative of several path results (documented
    over-approximation: serving methods return one shape family; raise
    paths and early outs contribute None/Unknown which we drop)."""
    if not vals:
        return Scalar(None)
    def score(v: AbsValue) -> int:
        if isinstance(v, (Arr, Tup, Tree)):
            return 3
        if isinstance(v, (Obj, ListOf, Scalar)):
            return 2
        return 0
    return max(vals, key=score)


def _elem_of(v: AbsValue) -> AbsValue:
    if isinstance(v, ListOf):
        return v.elem
    if isinstance(v, Tup):
        return _join(list(v.items))
    if isinstance(v, Arr) and v.ndim >= 1:
        return Arr(v.shape[1:], v.dtype, v.placement)
    return Unknown("iteration element")


def _unpack(v: AbsValue, n: int) -> List[AbsValue]:
    if isinstance(v, Tup) and len(v.items) == n:
        return list(v.items)
    if isinstance(v, Arr) and v.ndim >= 1 and \
            isinstance(v.shape[0], Known) and v.shape[0].v == n:
        return [Arr(v.shape[1:], v.dtype, v.placement) for _ in range(n)]
    return [Unknown("unpack") for _ in range(n)]


def _length(v: AbsValue) -> AbsValue:
    if isinstance(v, ListOf):
        return Scalar(v.length)
    if isinstance(v, Tup):
        return Scalar(len(v.items))
    if isinstance(v, Arr) and v.ndim >= 1:
        return Scalar(v.shape[0])
    return Unknown("len")


def _cast(name: str, v: AbsValue) -> AbsValue:
    if isinstance(v, Scalar):
        val = v.value
        if isinstance(val, Dim):
            return v if name == "int" else Scalar(Unbounded(name))
        if isinstance(val, (int, float, bool)):
            return Scalar({"int": int, "float": float,
                           "bool": bool}[name](val))
        return Scalar(Unbounded(f"{name}()"))
    if isinstance(v, Arr) and v.ndim == 0:
        return Scalar(Unbounded(f"{name}() host readback"))
    return Scalar(Unbounded(f"{name}()"))


def _minmax(name: str, args: List[AbsValue]) -> AbsValue:
    if len(args) == 1:
        return Scalar(Unbounded(name))
    dims = [as_dim(a) if isinstance(a, Scalar) else None for a in args]
    if any(d is None for d in dims):
        return Unknown(name)
    vals_list = [d.values() for d in dims]
    if any(v is None for v in vals_list):
        # bound an unbounded operand by a known one for min()
        return Scalar(Unbounded(name))
    if all(len(v) == 1 for v in vals_list):
        pick = (min if name == "min" else max)(v[0] for v in vals_list)
        return Scalar(pick)
    # elementwise over the distinguished non-Known dim (single-set case)
    sets = [d for d in dims if not isinstance(d, Known)]
    if len(sets) == 1:
        other = [d.values()[0] for d in dims if isinstance(d, Known)]
        f = min if name == "min" else max
        merged = {f([v] + other) for v in sets[0].values()}
        if len(merged) == 1:
            return Scalar(next(iter(merged)))
        return Scalar(FiniteSet(sorted(merged)))
    return Unknown(name)


def _range(args: List[AbsValue]) -> AbsValue:
    dims = [as_dim(a) if isinstance(a, Scalar) else None for a in args]
    if all(isinstance(d, Known) for d in dims) and dims:
        vals = [d.v for d in dims]  # type: ignore[union-attr]
        r = range(*vals)
        if len(r) <= MAX_PATHS:
            return Tup([Scalar(i) for i in r])
    return ListOf(Scalar(Unbounded("range")), maybe_empty=True)


def _py_arith(a, op: ast.operator, b):
    if isinstance(op, ast.Add):
        return a + b
    if isinstance(op, ast.Sub):
        return a - b
    if isinstance(op, ast.Mult):
        return a * b
    if isinstance(op, ast.FloorDiv):
        return a // b
    if isinstance(op, ast.Div):
        return a / b
    if isinstance(op, ast.Mod):
        return a % b
    if isinstance(op, ast.Pow):
        return a ** b
    raise TypeError("op")


def _dim_arith(a: Dim, op: ast.operator, b: Dim) -> Optional[Dim]:
    if isinstance(a, Known) and isinstance(b, Known):
        try:
            return dim_of(int(_py_arith(a.v, op, b.v)))
        except (TypeError, ZeroDivisionError):
            return None
    av, bv = a.values(), b.values()
    if av is None or bv is None:
        return Unbounded("arith over unbounded dim")
    if len(av) > 1 and len(bv) > 1:
        return Unbounded("arith over two abstract dims")
    try:
        vals = sorted({int(_py_arith(x, op, y)) for x in av for y in bv})
    except (TypeError, ZeroDivisionError):
        return None
    if len(vals) == 1:
        return Known(vals[0])
    if isinstance(a, IntRange) or isinstance(b, IntRange):
        return IntRange(vals[0], vals[-1])
    return FiniteSet(vals)


def _compare_one(left: AbsValue, op: ast.cmpop,
                 right: AbsValue) -> Optional[bool]:
    # identity / None tests over modelled host objects
    if isinstance(op, (ast.Is, ast.IsNot)):
        def nullness(v: AbsValue) -> Optional[bool]:
            if isinstance(v, Scalar) and v.value is None:
                return True
            if isinstance(v, (Obj, Arr, Tree, Tup, ListOf, DTypeVal)):
                return False
            if isinstance(v, Scalar) and isinstance(
                    v.value, (bool, int, float, str)):
                return False
            return None
        ln, rn = nullness(left), nullness(right)
        if ln is None or rn is None:
            return None
        same = ln and rn
        if not ln and not rn:
            return None if not isinstance(op, ast.Is) else None
        return same if isinstance(op, ast.Is) else not same
    if isinstance(left, Tup) and isinstance(right, Tup) and \
            isinstance(op, (ast.Eq, ast.NotEq)):
        lv = [as_dim(i) if isinstance(i, Scalar) else None
              for i in left.items]
        rv = [as_dim(i) if isinstance(i, Scalar) else None
              for i in right.items]
        if all(isinstance(d, Known) for d in lv) and \
                all(isinstance(d, Known) for d in rv):
            eq = [d.v for d in lv] == [d.v for d in rv]  # type: ignore
            return eq if isinstance(op, ast.Eq) else not eq
        return None
    ld = as_dim(left) if isinstance(left, Scalar) else None
    rd = as_dim(right) if isinstance(right, Scalar) else None
    if ld is None or rd is None:
        if isinstance(left, Scalar) and isinstance(right, Scalar) and \
                isinstance(left.value, (str, bool, int, float)) and \
                isinstance(right.value, (str, bool, int, float)):
            return _py_compare(left.value, op, right.value)
        return None
    lv, rv = ld.values(), rd.values()
    if lv is None or rv is None:
        return None
    lo_l, hi_l, lo_r, hi_r = min(lv), max(lv), min(rv), max(rv)
    if isinstance(op, ast.Lt):
        if hi_l < lo_r:
            return True
        if lo_l >= hi_r:
            return False
    elif isinstance(op, ast.LtE):
        if hi_l <= lo_r:
            return True
        if lo_l > hi_r:
            return False
    elif isinstance(op, ast.Gt):
        if lo_l > hi_r:
            return True
        if hi_l <= lo_r:
            return False
    elif isinstance(op, ast.GtE):
        if lo_l >= hi_r:
            return True
        if hi_l < lo_r:
            return False
    elif isinstance(op, (ast.Eq, ast.NotEq)):
        if len(lv) == 1 and len(rv) == 1:
            eq = lv[0] == rv[0]
            return eq if isinstance(op, ast.Eq) else not eq
        if hi_l < lo_r or lo_l > hi_r:
            return isinstance(op, ast.NotEq)
    return None


def _py_compare(a, op: ast.cmpop, b) -> Optional[bool]:
    try:
        if isinstance(op, ast.Eq):
            return a == b
        if isinstance(op, ast.NotEq):
            return a != b
        if isinstance(op, ast.Lt):
            return a < b
        if isinstance(op, ast.LtE):
            return a <= b
        if isinstance(op, ast.Gt):
            return a > b
        if isinstance(op, ast.GtE):
            return a >= b
    except TypeError:
        return None
    return None


def _load_of(target: ast.expr) -> ast.expr:
    import copy
    node = copy.deepcopy(target)
    for n in ast.walk(node):
        if hasattr(n, "ctx"):
            n.ctx = ast.Load()
    return node


_EXTRA_RULES = {
    "np.ndim": lambda args, kw: Scalar(args[0].ndim)
    if args and isinstance(args[0], Arr)
    else (Scalar(0) if args and isinstance(args[0], Scalar)
          else Unknown("ndim")),
}


# ----------------------------------------------------------------------
# config seeding + drivers
# ----------------------------------------------------------------------
def _engine_obj(env: dict) -> Obj:
    return Obj("InferenceEngine", {
        "params": Tree(COMMITTED, "params"),
        "_jit_prefill_chunk": Obj("jit"),
        "_jit_verify_k": Obj("jit"),
        "_decode_fn": Obj("fn"),
    })


def _pool_obj(env: dict, engine: Obj) -> Obj:
    S = int(env["num_slots"])
    attrs: Dict[str, AbsValue] = {
        "num_slots": Scalar(S),
        "capacity": Scalar(int(env["capacity"])),
        "starts": Arr((S,), "int32", HOST),
        "cache": DictVal({"cache_store": Tree(COMMITTED, "pool")}),
        "_sharding": Obj("sharding"),
        "spec": Obj("spec"),
        "_engine": engine,
        "_admit_jit": Obj("jit"),
        "_admit_rows_jit": Obj("jit"),
    }
    if env.get("paged"):
        P = int(env["num_pages"])
        pps = int(env["pages_per_slot"])
        attrs.update({
            "page_size": Scalar(int(env["page_size"])),
            "num_pages": Scalar(P),
            "pages_per_slot": Scalar(pps),
            "table": Arr((S, pps), "int32", HOST),
            "page_refs": Arr((P,), "int64", HOST),
            "prefix": Obj("PrefixCache") if env.get("use_prefix")
            else Scalar(None),
            "cow_copies": Scalar(0),
            "page_evictions": Scalar(0),
            "_jit_copy_page": Obj("jit"),
            "_jit_gather_pages": Obj("jit"),
            "_jit_scatter_pages": Obj("jit"),
            "_paged_decode_jit": Obj("jit"),
            "_paged_verify_jit": Obj("jit"),
            "_paged_chunk_jit": Obj("jit"),
            # the fused-kernel arms exist iff the env arms them
            # (``paged_kernel_active`` in ``_signature_env``); the
            # precise is-not-None nullness test then picks the dispatch
            # branch instead of forking both
            "_paged_decode_kernel_jit": Obj("jit")
            if env.get("paged_kernel_active") else Scalar(None),
            "_paged_verify_kernel_jit": Obj("jit")
            if env.get("paged_kernel_active") else Scalar(None),
        })
        return Obj("PagedKVPool", attrs)
    return Obj("SlotPool", attrs)


def _serving_obj(env: dict) -> Obj:
    engine = _engine_obj(env)
    pool = _pool_obj(env, engine)
    S = int(env["num_slots"])
    spec_k = int(env.get("spec_k") or 0)
    return Obj("ServingEngine", {
        "engine": engine,
        "pool": pool,
        "_paged": Scalar(bool(env.get("paged"))),
        "_use_prefix": Scalar(bool(env.get("use_prefix"))),
        "_stall_free": Scalar(bool(env.get("stall_free"))),
        "prefill_chunk": Scalar(int(env.get("prefill_chunk") or 0)),
        "prefill_token_budget": Scalar(
            int(env.get("prefill_token_budget") or 0)),
        "faults": Scalar(None),
        "_load": Scalar(None),
        "_spec": Obj("SpecConfig", {"k": Scalar(spec_k)})
        if spec_k else Scalar(None),
        "_drafter": Obj("Drafter") if spec_k else Scalar(None),
        "_jit_finite": Obj("jit") if env.get("guard_numerics")
        else Scalar(None),
        "temperature": Scalar(float(env.get("temperature", 1.0))),
        "top_k": Scalar(int(env.get("top_k") or 0)),
        "top_p": Scalar(float(env.get("top_p", 1.0))),
        "_greedy": Arr((), "bool", UNCOMMITTED),
        "_rng": Arr((2,), "uint32", HOST),
        "_current": Arr((S,), "int32", HOST),
        "_cur_dev": Arr((S,), "int32", COMMITTED),
        "_overlap": Scalar(bool(env.get("overlap"))),
        "_deferred": ListOf(Unknown("deferred fetch"), maybe_empty=True),
        "timers": Obj("opaque"),
        "_slot_req": Obj("opaque"),
        "tracer": Obj("opaque"),
        "metrics": Obj("opaque"),
        "timelines": Obj("opaque"),
        "scheduler": Obj("opaque"),
        "slo": Scalar(None),
        "step_id": Scalar(0),
        "_tokens_emitted": Scalar(0),
        "_prefill_queue": ListOf(Unknown("queue"), maybe_empty=True),
    })


def _request_obj(T: Dim) -> Obj:
    return Obj("Request", {
        "seed_len": Scalar(T),
        "seed_tokens": Arr((T,), "int32", HOST),
        "output_tokens": ListOf(Scalar(Unbounded("token")),
                                maybe_empty=True),
        "prompt_len": Scalar(T),
        "request_id": Scalar(Unbounded("rid")),
        "admit_time": Scalar(None),
        "first_token_time": Scalar(None),
        "slot": Scalar(None),
        "prefill_pos": Scalar(0),
        "chunks": Scalar(0),
        "eos_token_id": Scalar(None),
    })


def _max_group(env: dict, width: int) -> int:
    """Largest same-bucket admission group the token-budget grant can
    produce at ``width`` (mirrors ``FIFOScheduler.grant``: each
    admission is charged its padded bucket; the head may overshoot but
    a GROUP never exceeds budget // width; free slots bound it too)."""
    budget = int(env.get("prefill_token_budget") or 0)
    slots = int(env["num_slots"])
    if budget <= 0:
        return 1
    return max(1, min(slots, budget // width))


def _singleton_T(env: dict) -> IntRange:
    max_len = int(env.get("max_prompt_len")
                  or env.get("max_seed_len") or env["capacity"])
    hi = max_len
    if env.get("stall_free"):
        hi = min(hi, int(env["prefill_chunk"]))
    return IntRange(1, max(1, hi), "seed_len")


def _widths(env: dict) -> List[int]:
    """The reachable padded bucket widths for grouped (>=2) admissions."""
    hi = _singleton_T(env).hi
    cap = int(env["capacity"])
    out = []
    b = 16                       # _MIN_PREFILL_BUCKET
    while True:
        out.append(min(b, cap))
        if b >= hi:
            break
        b *= 2
    return sorted(set(out))


def run_drivers(interp: Interp) -> None:
    """Interpret every config-reachable serving entry point (the
    calling-context table — see module docstring)."""
    env = interp.env
    project = interp.project
    srv = _serving_obj(env)

    def call(obj: Obj, method: str, frame_args: Dict[str, AbsValue]):
        resolved = project.resolve_method(obj.kind, method)
        if resolved is None:
            return
        fn, mod = resolved
        params = [a.arg for a in fn.args.args]
        args = []
        for p in params[1:]:
            args.append(frame_args.get(p, Unknown(f"driver arg {p}")))
        interp.call_function(fn, mod, obj, args, {})

    finished = ListOf(Unknown("finished"), maybe_empty=True)

    # 1. singleton bucketed admission (whole-seed prefill at a padded
    #    power-of-two width)
    call(srv, "_admit", {
        "req": _request_obj(_singleton_T(env)),
        "finished": finished})

    if env.get("stall_free"):
        # 2. batched bucketed admission, per width (the reachable batch
        #    bucket set depends on the width through the token budget)
        for width in _widths(env):
            gmax = _max_group(env, width)
            if gmax < 2:
                continue
            group_n = IntRange(2, gmax, f"group@{width}")
            call(srv, "_admit_batch", {
                "group": ListOf(_request_obj(
                    IntRange(1, min(width, _singleton_T(env).hi))),
                    group_n, maybe_empty=False),
                "width": Scalar(width),
                "finished": finished})

        # 3. chunked prefill steps for long prompts
        max_len = int(env.get("max_prompt_len")
                      or env.get("max_seed_len") or env["capacity"])
        if max_len > int(env["prefill_chunk"] or 0):
            srv2 = _serving_obj(env)
            req = _request_obj(IntRange(1, max_len, "seed_len"))
            req.attrs["slot"] = Scalar(
                IntRange(0, int(env["num_slots"]) - 1, "slot"))
            req.attrs["prefill_pos"] = Scalar(
                IntRange(0, max_len - 1, "pos"))
            srv2.attrs["_prefill_queue"] = ListOf(req, maybe_empty=False)
            call(srv2, "_prefill_chunk_step", {"finished": finished})

    # 4. the decode step (and the numerics guard, when armed)
    call(srv, "_decode_step", {"finished": finished,
                               "t0": Scalar(0.0)})

    # 5. speculative verify step
    if env.get("spec_k"):
        call(srv, "_spec_decode_step", {"finished": finished,
                                        "t0": Scalar(0.0)})

    # 6. paged page management: CoW page copies (also pre-warmed by
    #    bind_engine with a self-copy at runtime)
    if env.get("paged"):
        pool = srv.attrs["pool"]
        cap = int(env["capacity"])
        call(pool, "ensure_writable", {
            "slot": Scalar(IntRange(0, int(env["num_slots"]) - 1)),
            "start": Scalar(IntRange(0, cap, "start")),
            "end": Scalar(IntRange(0, cap, "end")),
            "sync": Scalar(True)})

        # 7. cross-pool page transfer (the disaggregated prefill->decode
        #    handoff): id vectors are always sentinel-padded to
        #    pages_per_slot, so ONE signature covers every transfer
        #    (also pre-warmed by bind_engine with an all-sentinel copy)
        pps = int(env["pages_per_slot"])
        call(pool, "_dispatch_transfer", {
            "src_pool": _pool_obj(env, srv.attrs["engine"]),
            "src_vec": Arr((Known(pps),), "int32", HOST),
            "dst_vec": Arr((Known(pps),), "int32", HOST)})


def default_check_envs() -> List[dict]:
    """The representative configs a bare ``--check`` proves finite:
    the CI bench rows' serving arms (serving-stall stall-free + serial,
    kv-paging paged + dense).  ``--manifest`` runs replace these with
    the configs recorded in the manifest itself."""
    common = dict(top_k=0, top_p=1.0, greedy=False, temperature=1.0,
                  spec_k=0, guard_numerics=False)
    stall = dict(num_slots=8, capacity=1024, prefill_chunk=256,
                 prefill_token_budget=1024, paged=False, page_size=0,
                 num_pages=0, pages_per_slot=0, use_prefix=False,
                 vocab_size=512, max_prompt_len=760, **common)
    paging = dict(num_slots=8, capacity=256, prefill_chunk=32,
                  prefill_token_budget=64, paged=True, page_size=32,
                  num_pages=32, pages_per_slot=8, use_prefix=True,
                  vocab_size=512, max_seed_len=160, **common)
    return [
        dict(stall, stall_free=True),
        dict(stall, stall_free=False, prefill_chunk=0,
             prefill_token_budget=0),
        dict(paging, stall_free=True),
        dict(paging, paged=False, page_size=0, num_pages=0,
             pages_per_slot=0, num_slots=4, use_prefix=False,
             stall_free=True),
        # the serving-decode bench row's fused-kernel arm: same paged
        # config, decode/verify dispatch through the Pallas kernel jits
        dict(paging, stall_free=True, paged_kernel="on",
             paged_kernel_active=True),
        # the serving-tp bench row's sharded arm: a (data, model) mesh
        # changes ONLY array placements, never a traced shape, so its
        # enumerated signature set must be identical to the dense env's
        # (mesh_data/mesh_model ride along in _signature_env for config
        # identity; the drivers ignore unknown keys)
        dict(stall, stall_free=True, mesh_data=4, mesh_model=2),
    ]


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
class EnumResult:
    def __init__(self, programs: Dict[str, List[str]],
                 findings: List[Finding]):
        self.programs = programs
        self.findings = findings


def enumerate_signatures(env: dict, root: str,
                         project: Optional[ProjectIndex] = None
                         ) -> EnumResult:
    """Statically enumerate the reachable manifest-signature set per
    watched program for one config ``env`` (the dict
    ``ServingEngine._signature_env`` exports).  Returns the programs
    map plus ``signature-escape`` / ``unbounded-signature`` findings
    anchored at the offending watched call sites."""
    project = project or ProjectIndex(root)
    interp = Interp(project, env)
    run_drivers(interp)
    programs: Dict[str, set] = {}
    findings: List[Finding] = []
    seen_failures = set()
    for rec in interp.records:
        try:
            sigs = expand_signatures(rec.args, rec.kwargs)
        except SignatureError as e:
            key = (rec.program, rec.rel, rec.line, e.kind)
            if key in seen_failures:
                continue
            seen_failures.add(key)
            findings.append(Finding(
                rule=e.kind, severity=ERROR, path=rec.rel,
                line=rec.line, col=1,
                message=f"watched program `{rec.program}`: {e} — the "
                        "zero-recompile invariant cannot be proven for "
                        "this call",
                func=rec.program))
            continue
        programs.setdefault(rec.program, set()).update(sigs)
    out = {name: sorted(vals) for name, vals in sorted(programs.items())}
    return EnumResult(out, findings + interp.findings)


def enumerate_union(envs: Iterable[dict], root: str,
                    project: Optional[ProjectIndex] = None) -> EnumResult:
    """Union of :func:`enumerate_signatures` across configs — the shape
    a bench row's manifest has (several arms share one engine)."""
    project = project or ProjectIndex(root)
    programs: Dict[str, set] = {}
    findings: List[Finding] = []
    seen = set()
    for env in envs:
        res = enumerate_signatures(env, root, project)
        for name, sigs in res.programs.items():
            programs.setdefault(name, set()).update(sigs)
        for f in res.findings:
            key = (f.rule, f.path, f.line)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return EnumResult(
        {n: sorted(v) for n, v in sorted(programs.items())}, findings)


def diff_manifest(static: Dict[str, List[str]],
                  manifest: Dict[str, List[str]]) -> List[str]:
    """Human-readable divergences between the statically enumerated
    programs and a runtime warmup manifest.  Empty list == exact match.

    Both directions are failures: a runtime signature the static set
    lacks means the interpreter (or a driver) missed a reachable
    shape; a static signature the runtime never hit means the warmup
    sweep under-covers and that bucket will compile post-warmup."""
    out: List[str] = []
    for name in sorted(set(static) | set(manifest)):
        s = set(static.get(name, ()))
        m = set(manifest.get(name, ()))
        if name not in manifest:
            out.append(f"{name}: statically reachable but absent from "
                       f"the runtime manifest ({len(s)} signature(s))")
            continue
        if name not in static:
            out.append(f"{name}: in the runtime manifest but not "
                       f"statically reachable ({len(m)} signature(s))")
            continue
        for sig in sorted(s - m):
            out.append(f"{name}: static-only {sig} (warmup sweep never "
                       "hit this bucket — it will compile post-warmup)")
        for sig in sorted(m - s):
            out.append(f"{name}: runtime-only {sig} (the static "
                       "enumeration missed a reachable shape)")
    return out
