"""Per-op abstract shape/dtype transfer functions for graftcheck.

Each rule maps abstract operands (:mod:`absdomain` values) to the
abstract result of one numpy/jnp/lax operation.  The registry is keyed
by *canonical* dotted name — the interpreter normalises whatever the
module imported (``import numpy as np``, ``from jax import numpy as
jnp``) to the ``np.`` / ``jnp.`` / ``jax.lax.`` / ``jax.random.``
prefixes before lookup.

Rules are deliberately forgiving: an operand combination a rule cannot
handle returns :class:`~.absdomain.Unknown` rather than raising, so
imprecision surfaces as a ``signature-escape`` finding only if the
value actually reaches a watched jit operand.

Placement discipline (the placement-mix rule's input):

* ``np.*`` constructors produce HOST arrays (numpy-backed operands are
  layout-neutral at a jit boundary — they adopt the executable's
  layout);
* ``jnp.*`` constructors produce UNCOMMITTED device arrays (default
  layout, the PR-5 double-compile hazard);
* ``jnp.asarray``/conversions *preserve* the operand's placement —
  converting a host buffer does not commit it;
* only ``jax.device_put`` (modelled in the interpreter, where the
  sharding operand is visible) yields COMMITTED.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from .absdomain import (HOST, UNCOMMITTED, AbsValue, Arr, Dim, FiniteSet,
                        IntRange, Known, Obj, Scalar, Tree, Tup, Unknown,
                        dim_of)


class DTypeVal(AbsValue):
    """A dtype object (``jnp.int32``) flowing as a value."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"DTypeVal({self.name})"


DTYPE_NAMES = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "bfloat16", "float32", "float64", "bool", "bool_",
}


def dtype_name(v: Any, default: str) -> str:
    if isinstance(v, DTypeVal):
        return "bool" if v.name == "bool_" else v.name
    if isinstance(v, Scalar) and isinstance(v.value, str) \
            and v.value in DTYPE_NAMES:
        return "bool" if v.value == "bool_" else v.value
    return default


def as_dim(v: Any) -> Dim:
    """Coerce an abstract value (or int) to a Dim; Unknown on failure."""
    if isinstance(v, Scalar):
        try:
            return v.as_dim()
        except TypeError:
            return None  # type: ignore[return-value]
    if isinstance(v, Arr) and v.ndim == 0:
        # a 0-d int array used as a size — not statically enumerable
        return None  # type: ignore[return-value]
    try:
        return dim_of(v)
    except TypeError:
        return None  # type: ignore[return-value]


def shape_from(v: AbsValue) -> Optional[List[Dim]]:
    """Parse a shape operand: an int scalar, or a Tup of int scalars."""
    if isinstance(v, Tup):
        dims = [as_dim(x) for x in v.items]
        if any(d is None for d in dims):
            return None
        return dims  # type: ignore[return-value]
    d = as_dim(v)
    return None if d is None else [d]


def _broadcast_dim(a: Dim, b: Dim) -> Dim:
    av, bv = a.values(), b.values()
    if av == (1,):
        return b
    if bv == (1,):
        return a
    if isinstance(a, Known):
        return b if not isinstance(b, Known) else a
    return a


def broadcast_shapes(a: Sequence[Dim], b: Sequence[Dim]) -> List[Dim]:
    out: List[Dim] = []
    ra, rb = list(a)[::-1], list(b)[::-1]
    for i in range(max(len(ra), len(rb))):
        if i >= len(ra):
            out.append(rb[i])
        elif i >= len(rb):
            out.append(ra[i])
        else:
            out.append(_broadcast_dim(ra[i], rb[i]))
    return out[::-1]


def merge_placement(vals: Sequence[AbsValue]) -> str:
    for v in vals:
        if isinstance(v, (Arr, Tree)) and v.placement == UNCOMMITTED:
            return UNCOMMITTED
    return HOST


def binop(a: AbsValue, b: AbsValue) -> AbsValue:
    """Elementwise arithmetic/comparison between abstract operands."""
    if isinstance(a, Arr) and isinstance(b, Arr):
        return Arr(broadcast_shapes(a.shape, b.shape), a.dtype,
                   merge_placement((a, b)))
    if isinstance(a, Arr):
        return a
    if isinstance(b, Arr):
        return b
    return Unknown("scalar binop")


# ----------------------------------------------------------------------
# rule implementations
# ----------------------------------------------------------------------
def _constructor(placement: str, default_dtype: str):
    def rule(args, kwargs):
        if not args:
            return Unknown("constructor without shape")
        shape = shape_from(args[0])
        if shape is None:
            return Unknown("unresolvable shape operand")
        dt = default_dtype
        if len(args) > 1:
            dt = dtype_name(args[1], dt)
        dt = dtype_name(kwargs.get("dtype"), dt) if "dtype" in kwargs else dt
        return Arr(shape, dt, placement)
    return rule


def _full(placement: str):
    def rule(args, kwargs):
        if len(args) < 2:
            return Unknown("full without fill value")
        shape = shape_from(args[0])
        if shape is None:
            return Unknown("unresolvable shape operand")
        fill = args[1]
        dt = "float64" if placement == HOST else "float32"
        if isinstance(fill, Scalar):
            if isinstance(fill.value, bool):
                dt = "bool"
            elif isinstance(fill.value, (int, Dim)) \
                    and not isinstance(fill.value, bool):
                dt = "int64" if placement == HOST else "int32"
            elif isinstance(fill.value, float):
                dt = "float64" if placement == HOST else "float32"
        if len(args) > 2:
            dt = dtype_name(args[2], dt)
        dt = dtype_name(kwargs.get("dtype"), dt) if "dtype" in kwargs else dt
        return Arr(shape, dt, placement)
    return rule


def _asarray(placement_default: str):
    def rule(args, kwargs):
        if not args:
            return Unknown("asarray()")
        x = args[0]
        dt = args[1] if len(args) > 1 else kwargs.get("dtype")
        if isinstance(x, Arr):
            out = Arr(x.shape, dtype_name(dt, x.dtype), x.placement)
            return out
        if isinstance(x, Scalar):
            v = x.value
            if isinstance(v, bool):
                base = "bool"
            elif isinstance(v, (int, Dim)):
                base = "int32"
            elif isinstance(v, float):
                base = "float32" if placement_default == UNCOMMITTED \
                    else "float64"
            else:
                return Unknown(f"asarray of {v!r}")
            # scalar conversions inherit HOST: the value came from host
            # python, the array adopts the consumer's layout
            return Arr((), dtype_name(dt, base), HOST)
        if isinstance(x, Tup):
            dims = [as_dim(i) for i in x.items]
            if all(d is not None for d in dims):
                # jnp default-int is int32 (x64 disabled); np is int64
                base = "int32" if placement_default == UNCOMMITTED \
                    else "int64"
                return Arr((Known(len(dims)),),
                           dtype_name(dt, base), HOST)
        if isinstance(x, Tree):
            return x
        return Unknown("asarray of unknown operand")
    return rule


def _concatenate(args, kwargs):
    if not args or not isinstance(args[0], Tup):
        return Unknown("concatenate needs a literal sequence")
    arrs = [a for a in args[0].items]
    if not arrs or not all(isinstance(a, Arr) for a in arrs):
        return Unknown("concatenate of non-arrays")
    axis = 0
    ax = kwargs.get("axis", args[1] if len(args) > 1 else None)
    if ax is not None:
        d = as_dim(ax)
        if d is None or not isinstance(d, Known):
            return Unknown("concatenate with non-literal axis")
        axis = d.v
    first: Arr = arrs[0]
    nd = first.ndim
    if axis < 0:
        axis += nd
    if not 0 <= axis < nd:
        return Unknown("concatenate axis out of range")
    total = 0
    parts = []
    for a in arrs:
        if a.ndim != nd:
            return Unknown("concatenate rank mismatch")
        d = a.shape[axis]
        if not isinstance(d, Known):
            parts = None
            break
        total += d.v
        parts = parts if parts is None else parts + [d]
    shape = list(first.shape)
    if parts is None:
        from .absdomain import Unbounded
        shape[axis] = Unbounded("concatenate of symbolic lengths")
    else:
        shape[axis] = Known(total)
    return Arr(shape, first.dtype, merge_placement(arrs))


def _broadcast_to(args, kwargs):
    if len(args) < 2 or not isinstance(args[0], Arr):
        return Unknown("broadcast_to operands")
    shape = shape_from(args[1])
    if shape is None:
        return Unknown("broadcast_to shape")
    return Arr(shape, args[0].dtype, args[0].placement)


def _reshape(args, kwargs):
    if len(args) < 2 or not isinstance(args[0], Arr):
        return Unknown("reshape operands")
    shape = shape_from(args[1])
    if shape is None:
        return Unknown("reshape shape")
    if any(isinstance(d, Known) and d.v == -1 for d in shape):
        # -1 wildcard: only resolvable when every other dim and the
        # operand's total size are Known
        src = 1
        for d in args[0].shape:
            if not isinstance(d, Known):
                return Unknown("reshape -1 over symbolic operand")
            src *= d.v
        rest = 1
        for d in shape:
            if isinstance(d, Known) and d.v != -1:
                rest *= d.v
            elif not isinstance(d, Known):
                return Unknown("reshape -1 with symbolic dims")
        shape = [Known(src // max(rest, 1)) if
                 (isinstance(d, Known) and d.v == -1) else d for d in shape]
    return Arr(shape, args[0].dtype, args[0].placement)


def _arange(args, kwargs):
    if not args:
        return Unknown("arange()")
    n = as_dim(args[0])
    if n is None:
        return Unknown("arange of non-int")
    dt = dtype_name(kwargs.get("dtype", args[1] if len(args) > 1 else None),
                    "int32")
    return Arr((n,), dt, UNCOMMITTED)


def _take(args, kwargs):
    # jnp.take(x, idx, axis=k): x.shape with axis k replaced by idx.shape
    if len(args) < 2 or not isinstance(args[0], Arr):
        return Unknown("take operands")
    x, idx = args[0], args[1]
    if not isinstance(idx, Arr):
        return Unknown("take with non-array indices")
    ax = kwargs.get("axis", args[2] if len(args) > 2 else None)
    if ax is None:
        return Arr(idx.shape, x.dtype, merge_placement((x, idx)))
    d = as_dim(ax)
    if d is None or not isinstance(d, Known):
        return Unknown("take with non-literal axis")
    axis = d.v if d.v >= 0 else d.v + x.ndim
    if not 0 <= axis < x.ndim:
        return Unknown("take axis out of range")
    shape = list(x.shape[:axis]) + list(idx.shape) + list(x.shape[axis + 1:])
    return Arr(shape, x.dtype, merge_placement((x, idx)))


def _take_along_axis(args, kwargs):
    if len(args) < 2 or not all(isinstance(a, Arr) for a in args[:2]):
        return Unknown("take_along_axis operands")
    x, idx = args[0], args[1]
    return Arr(idx.shape, x.dtype, merge_placement((x, idx)))


def _where(args, kwargs):
    if len(args) == 3:
        arrs = [a for a in args if isinstance(a, Arr)]
        if not arrs:
            return Unknown("where of scalars")
        shape = arrs[0].shape
        for a in arrs[1:]:
            shape = broadcast_shapes(shape, a.shape)
        out = args[1] if isinstance(args[1], Arr) else arrs[0]
        return Arr(shape, out.dtype, merge_placement(arrs))
    return Unknown("where without branches")


def _elementwise(args, kwargs):
    arrs = [a for a in args if isinstance(a, Arr)]
    if not arrs:
        return Unknown("elementwise of scalars")
    shape = arrs[0].shape
    for a in arrs[1:]:
        shape = broadcast_shapes(shape, a.shape)
    return Arr(shape, arrs[0].dtype, merge_placement(arrs))


def _comparison(args, kwargs):
    out = _elementwise(args, kwargs)
    return out.with_dtype("bool") if isinstance(out, Arr) else out


def _reduction(args, kwargs):
    if not args or not isinstance(args[0], Arr):
        return Unknown("reduction operand")
    x: Arr = args[0]
    ax = kwargs.get("axis", args[1] if len(args) > 1 else None)
    if ax is None:
        return Arr((), x.dtype, x.placement)
    axes = []
    if isinstance(ax, Tup):
        for a in ax.items:
            d = as_dim(a)
            if d is None or not isinstance(d, Known):
                return Unknown("reduction with symbolic axes")
            axes.append(d.v % max(x.ndim, 1))
    else:
        d = as_dim(ax)
        if d is None or not isinstance(d, Known):
            return Unknown("reduction with symbolic axis")
        axes.append(d.v % max(x.ndim, 1))
    shape = [s for i, s in enumerate(x.shape) if i not in axes]
    return Arr(shape, x.dtype, x.placement)


def _bool_reduction(args, kwargs):
    out = _reduction(args, kwargs)
    return out.with_dtype("bool") if isinstance(out, Arr) else out


def _argmax(args, kwargs):
    out = _reduction(args, kwargs)
    return out.with_dtype("int32") if isinstance(out, Arr) else out


def _dynamic_slice_in_dim(args, kwargs):
    # lax.dynamic_slice_in_dim(x, start, size, axis)
    if len(args) < 3 or not isinstance(args[0], Arr):
        return Unknown("dynamic_slice_in_dim operands")
    x: Arr = args[0]
    size = as_dim(args[2])
    if size is None:
        return Unknown("dynamic_slice_in_dim with symbolic size")
    ax = kwargs.get("axis", args[3] if len(args) > 3 else Scalar(0))
    d = as_dim(ax)
    if d is None or not isinstance(d, Known):
        return Unknown("dynamic_slice_in_dim axis")
    axis = d.v % max(x.ndim, 1)
    shape = list(x.shape)
    if axis >= len(shape):
        return Unknown("dynamic_slice_in_dim axis out of range")
    shape[axis] = size
    return Arr(shape, x.dtype, x.placement)


def _dynamic_update_slice(args, kwargs):
    # result has the DESTINATION's shape (both _in_dim and plain forms)
    if not args or not isinstance(args[0], Arr):
        return Unknown("dynamic_update_slice operands")
    return args[0]


def _random_split(args, kwargs):
    # legacy PRNG keys: split(key[, n]) -> uint32 (n, 2)
    n: Dim = Known(2)
    if len(args) > 1:
        d = as_dim(args[1])
        if d is None:
            return Unknown("random.split count")
        n = d
    return Arr((n, Known(2)), "uint32", HOST)


def _prng_key(args, kwargs):
    return Arr((Known(2),), "uint32", HOST)


def _random_categorical(args, kwargs):
    if len(args) < 2 or not isinstance(args[1], Arr):
        return Unknown("categorical operands")
    logits: Arr = args[1]
    return Arr(logits.shape[:-1], "int32", logits.placement)


def _device_put(args, kwargs):
    from .absdomain import COMMITTED
    if not args:
        return Unknown("device_put()")
    x = args[0]
    if isinstance(x, Arr):
        return x.with_placement(COMMITTED)
    if isinstance(x, Tree):
        return Tree(COMMITTED, x.label)
    return Unknown("device_put of unknown operand")


RULES: Dict[str, Callable[[List[AbsValue], Dict[str, AbsValue]], AbsValue]] = {
    # constructors
    "np.zeros": _constructor(HOST, "float64"),
    "np.ones": _constructor(HOST, "float64"),
    "np.empty": _constructor(HOST, "float64"),
    "np.full": _full(HOST),
    "jnp.zeros": _constructor(UNCOMMITTED, "float32"),
    "jnp.ones": _constructor(UNCOMMITTED, "float32"),
    "jnp.full": _full(UNCOMMITTED),
    "np.asarray": _asarray(HOST),
    "np.array": _asarray(HOST),
    "jnp.asarray": _asarray(UNCOMMITTED),
    "jnp.array": _asarray(UNCOMMITTED),
    "np.arange": _arange,
    "jnp.arange": _arange,
    # structure
    "np.concatenate": _concatenate,
    "jnp.concatenate": _concatenate,
    "np.reshape": _reshape,
    "jnp.reshape": _reshape,
    "np.broadcast_to": _broadcast_to,
    "jnp.broadcast_to": _broadcast_to,
    "np.take": _take,
    "jnp.take": _take,
    "np.take_along_axis": _take_along_axis,
    "jnp.take_along_axis": _take_along_axis,
    "jnp.where": _where,
    "np.where": _where,
    # elementwise / reductions
    "np.minimum": _elementwise,
    "np.maximum": _elementwise,
    "jnp.minimum": _elementwise,
    "jnp.maximum": _elementwise,
    "np.clip": _elementwise,
    "jnp.clip": _elementwise,
    "np.isfinite": _comparison,
    "jnp.isfinite": _comparison,
    "np.sum": _reduction,
    "jnp.sum": _reduction,
    "np.all": _bool_reduction,
    "jnp.all": _bool_reduction,
    "np.any": _bool_reduction,
    "jnp.any": _bool_reduction,
    "np.argmax": _argmax,
    "jnp.argmax": _argmax,
    # lax
    "jax.lax.dynamic_slice_in_dim": _dynamic_slice_in_dim,
    "jax.lax.dynamic_update_slice": _dynamic_update_slice,
    "jax.lax.dynamic_update_slice_in_dim": _dynamic_update_slice,
    # random / placement
    "jax.random.split": _random_split,
    "jax.random.PRNGKey": _prng_key,
    "jax.random.categorical": _random_categorical,
    "jax.device_put": _device_put,
}


# methods on abstract arrays: x.astype(dt), x.reshape(...), x.copy(), ...
def method_call(recv: AbsValue, name: str, args: List[AbsValue],
                kwargs: Dict[str, AbsValue]) -> AbsValue:
    if isinstance(recv, Arr):
        if name == "astype":
            if args:
                return recv.with_dtype(dtype_name(args[0], recv.dtype))
            return recv
        if name == "reshape":
            shape_arg = args[0] if len(args) == 1 else Tup(args)
            return _reshape([recv, shape_arg], {})
        if name == "copy":
            return recv
        if name == "sum":
            return _reduction([recv] + args, kwargs)
        if name in ("tolist", "item"):
            return Unknown(f".{name}() materialises host values")
        if name == "transpose":
            if all(isinstance(as_dim(a), Known) for a in args) \
                    and len(args) == recv.ndim:
                perm = [as_dim(a).v for a in args]
                return Arr([recv.shape[p] for p in perm], recv.dtype,
                           recv.placement)
            return Unknown("transpose with symbolic permutation")
    if isinstance(recv, Tree):
        # dict-style access on an opaque pytree stays opaque
        if name in ("get", "copy", "items", "keys", "values"):
            return Tree(recv.placement, recv.label)
    return Unknown(f"method .{name}() on {type(recv).__name__}")
