"""Findings baseline: grandfathered debt that should not fail the gate.

A baseline file is a JSON document of finding fingerprints (see
:func:`~.findings.assign_fingerprints` — keyed on rule + file +
function + normalised source text, *not* line numbers, so unrelated
edits don't invalidate it).  The workflow::

    bin/graftlint pkg/ --write-baseline graftlint_baseline.json  # freeze
    bin/graftlint pkg/ --baseline graftlint_baseline.json        # gate

Baselined findings are still printed (tagged ``[baselined]``) but do
not count toward the error total.  Fixing the underlying code makes the
stale entry harmless; ``--write-baseline`` regenerates a minimal file.
The serving/telemetry gate ships with *no* baseline — it holds at zero
outright — but the mechanism is what lets the gate extend to older
packages without a flag day.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Set

from .findings import Finding

VERSION = 1


def load_baseline(path: str) -> Set[str]:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"{path}: not a graftlint baseline file")
    return {entry["fingerprint"] for entry in doc["findings"]
            if isinstance(entry, dict) and "fingerprint" in entry}


def write_baseline(path: str, findings: Iterable[Finding]) -> int:
    entries: List[dict] = []
    for f in sorted(findings, key=lambda x: x.sort_key()):
        if f.suppressed:
            continue
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "message": f.message,
        })
    with open(path, "w") as fh:
        json.dump({"version": VERSION, "findings": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return len(entries)


def apply_baseline(findings: Iterable[Finding], fingerprints: Set[str]) -> int:
    n = 0
    for f in findings:
        if not f.suppressed and f.fingerprint in fingerprints:
            f.baselined = True
            n += 1
    return n
