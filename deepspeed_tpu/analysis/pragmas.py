"""Per-line suppression pragmas.

Syntax (trailing comment on the offending line, or a comment-only line
immediately above it)::

    x = pool.at[slot].set(v)  # graftlint: allow[unsafe-scatter] -- slot is clamped upstream
    # graftlint: allow[hot-loop-host-sync] -- the one deliberate sync per step
    out = np.asarray(dev)

Multiple rules may be listed (``allow[rule-a,rule-b]``) and ``*``
matches every rule.  The ``-- reason`` clause is mandatory: a pragma
without one is itself reported as a ``pragma-missing-reason`` error so
suppressions always document *why* the invariant does not apply.
Pragmas that never matched a finding are reported as ``unused-pragma``
warnings so stale allowances get cleaned up.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\[([A-Za-z0-9_\-*,\s]+)\]"
    r"(?:\s*--\s*(.*\S))?\s*$")


@dataclass
class Pragma:
    line: int
    rules: Set[str]
    reason: str
    comment_only: bool
    used: bool = False

    def matches(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


@dataclass
class PragmaIndex:
    by_line: Dict[int, Pragma] = field(default_factory=dict)

    @classmethod
    def from_source(cls, source: str) -> "PragmaIndex":
        idx = cls()
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            comment_only = text.strip().startswith("#")
            idx.by_line[lineno] = Pragma(lineno, rules, reason, comment_only)
        return idx

    def lookup(self, line: int, rule: str) -> Optional[Pragma]:
        """Pragma governing a finding at ``line`` for ``rule``.

        Checks the finding's own line first, then a comment-only pragma
        on the line directly above (the multi-line-statement escape
        hatch).
        """
        p = self.by_line.get(line)
        if p is not None and p.matches(rule):
            return p
        above = self.by_line.get(line - 1)
        if above is not None and above.comment_only and above.matches(rule):
            return above
        return None

    def all_pragmas(self) -> List[Pragma]:
        return [self.by_line[k] for k in sorted(self.by_line)]
