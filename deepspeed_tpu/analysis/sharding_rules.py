"""graftcheck sharding-consistency rules (the ``--check`` tier).

Four rules guard the mesh/sharding seams ahead of multi-chip serving
(ROADMAP item 1).  All are per-module AST rules that plug into the
same runner as the graftlint incident rules:

=========================  ==============================================
rule id                    invariant
=========================  ==============================================
mesh-axis-unknown          every axis name in a ``PartitionSpec`` must be
                           an axis the mesh actually declares (t5x-style
                           LogicalAxisRules validation) — a typo'd axis
                           silently replicates instead of sharding
shard-indivisible          a dim sharded over a mesh axis must be
                           statically divisible by that axis's declared
                           size, or GSPMD pads/reshards silently
donation-alias-mismatch    a ``donate_argnums`` operand must flow into
                           the traced function's results — otherwise the
                           donated buffer cannot alias any output and the
                           donation is a silent no-op (or an XLA error
                           once layouts differ)
placement-mix              traced code must not combine a committed
                           (``jax.device_put`` with sharding) value and a
                           fresh uncommitted ``jnp.*`` allocation in one
                           op: the PR-5/PR-8 double-executable class.
                           numpy-derived values are neutral — they adopt
                           the committed layout (the known-FP guard)
=========================  ==============================================

Axis universes and sizes are only trusted when they are *statically
declared* (string-literal ``*_AXIS`` constants / ``MESH_AXES`` tuples,
int-literal ``MeshConfig``/``build_mesh`` keywords).  Anything dynamic
makes the rule stay silent rather than guess.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .dataflow import (flatten_statements, node_path, reads_tainted,
                       target_paths, walk_exprs)
from .findings import ERROR, Finding
from .rules import ModuleContext, Rule

#: allocators whose results carry an *uncommitted* default layout
_UNCOMMITTED_ALLOCS = {
    "jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty", "jnp.arange",
    "jnp.zeros_like", "jnp.ones_like", "jnp.full_like",
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
}
#: host-side allocators: neutral, they adopt whatever layout they meet
_HOST_ALLOCS = {
    "np.zeros", "np.ones", "np.full", "np.empty", "np.asarray",
    "np.array", "np.arange", "numpy.zeros", "numpy.asarray",
}


def _pspec_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to ``jax.sharding.PartitionSpec``."""
    out = {"PartitionSpec", "jax.sharding.PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and \
                (node.module or "").endswith("sharding"):
            for al in node.names:
                if al.name == "PartitionSpec":
                    out.add(al.asname or al.name)
    return out


def _namedsharding_aliases(tree: ast.Module) -> Set[str]:
    out = {"NamedSharding", "jax.sharding.NamedSharding"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and \
                (node.module or "").endswith("sharding"):
            for al in node.names:
                if al.name == "NamedSharding":
                    out.add(al.asname or al.name)
    return out


def _module_axis_decls(tree: ast.Module) -> Tuple[Set[str], Dict[str, str]]:
    """Axis names a module itself declares.

    Returns ``(axes, const_map)``: string constants assigned to
    ``*_AXIS`` names, string elements of ``*_AXES`` tuples, and axis
    tuples passed to ``Mesh(...)`` / ``ProcessTopology([...], ...)``
    constructors.  ``const_map`` maps the constant NAME to its axis
    string so ``PartitionSpec(MODEL_AXIS)`` resolves.
    """
    axes: Set[str] = set()
    const_map: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            v = node.value
            if name.endswith("_AXIS") and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                axes.add(v.value)
                const_map[name] = v.value
            elif name.endswith("_AXES") and isinstance(v, ast.Tuple):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        axes.add(e.value)
        elif isinstance(node, ast.Call):
            fn = node.func
            ctor = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if ctor in ("Mesh", "ProcessTopology") and node.args:
                cand = node.args[1] if ctor == "Mesh" and \
                    len(node.args) > 1 else node.args[0]
                if ctor == "ProcessTopology":
                    cand = node.args[0]
                if isinstance(cand, (ast.Tuple, ast.List)):
                    for e in cand.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            axes.add(e.value)
    return axes, const_map


_MESH_MODULE_CACHE: Dict[str, Tuple[Set[str], Dict[str, str]]] = {}


def declared_mesh_axes(ctx_path: str) -> Tuple[Set[str], Dict[str, str]]:
    """The project's mesh-axis universe: parsed from
    ``deepspeed_tpu/parallel/mesh.py``, located by walking up from the
    analyzed file.  Unlocatable (fixture tests) → empty, and the rules
    fall back to what the module itself declares."""
    d = os.path.dirname(os.path.abspath(ctx_path))
    for _ in range(8):
        cand = os.path.join(d, "deepspeed_tpu", "parallel", "mesh.py")
        if os.path.isfile(cand):
            if cand not in _MESH_MODULE_CACHE:
                try:
                    with open(cand, encoding="utf-8") as fh:
                        tree = ast.parse(fh.read())
                    _MESH_MODULE_CACHE[cand] = _module_axis_decls(tree)
                except (OSError, SyntaxError):
                    _MESH_MODULE_CACHE[cand] = (set(), {})
            return _MESH_MODULE_CACHE[cand]
        nxt = os.path.dirname(d)
        if nxt == d:
            break
        d = nxt
    return set(), {}


def _spec_axis_entries(call: ast.Call) -> List[Tuple[ast.expr, List[str]]]:
    """(node, axis names) per PartitionSpec entry that names axes via
    string literals or tuples of string literals.  Name references are
    returned with the *constant name* prefixed ``@`` for resolution."""
    out: List[Tuple[ast.expr, List[str]]] = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg, [arg.value]))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            names = []
            for e in arg.elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    names.append(e.value)
                elif isinstance(e, ast.Name):
                    names.append("@" + e.id)
                elif isinstance(e, ast.Attribute):
                    names.append("@" + e.attr)
            if names:
                out.append((arg, names))
        elif isinstance(arg, ast.Name):
            out.append((arg, ["@" + arg.id]))
        elif isinstance(arg, ast.Attribute):
            out.append((arg, ["@" + arg.attr]))
    return out


class MeshAxisUnknownRule(Rule):
    id = "mesh-axis-unknown"
    severity = ERROR
    short = ("PartitionSpec names a mesh axis the declared mesh does "
             "not have")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        proj_axes, proj_consts = declared_mesh_axes(ctx.path)
        mod_axes, mod_consts = _module_axis_decls(ctx.tree)
        axes = proj_axes | mod_axes
        consts = dict(proj_consts)
        consts.update(mod_consts)
        if not axes:
            return  # no statically-declared mesh anywhere: stay silent
        pspec = _pspec_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            p = node_path(node.func)
            if p not in pspec:
                continue
            for entry, names in _spec_axis_entries(node):
                for name in names:
                    if name.startswith("@"):
                        # a *_AXIS constant reference: resolvable ones
                        # are checked, anything else is dynamic → skip
                        resolved = consts.get(name[1:])
                        if resolved is None or resolved in axes:
                            continue
                        name = resolved
                    if name not in axes:
                        yield self.finding(
                            ctx, entry,
                            f"PartitionSpec axis `{name}` is not a "
                            f"declared mesh axis (mesh declares: "
                            f"{', '.join(sorted(axes))})")


def _literal_shape(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The shape of a literal allocator call (``jnp.zeros((8, 16))``)."""
    if not call.args:
        return None
    sh = call.args[0]
    if isinstance(sh, ast.Constant) and isinstance(sh.value, int):
        return (sh.value,)
    if isinstance(sh, (ast.Tuple, ast.List)):
        dims = []
        for e in sh.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                dims.append(e.value)
            else:
                return None
        return tuple(dims)
    return None


def _axis_size_hints(tree: ast.Module) -> Dict[str, int]:
    """Int-literal axis sizes declared in the module: keyword args of
    ``MeshConfig``/``build_mesh``/``initialize_mesh`` calls."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name not in ("MeshConfig", "build_mesh", "initialize_mesh"):
            continue
        for kw in node.keywords:
            if kw.arg and isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int) and kw.value.value > 0:
                out[kw.arg] = kw.value.value
    return out


class ShardIndivisibleRule(Rule):
    id = "shard-indivisible"
    severity = ERROR
    short = ("array dim not statically divisible by the mesh axis it "
             "is sharded over")

    _SINKS = {"jax.device_put", "jax.lax.with_sharding_constraint",
              "with_sharding_constraint", "device_put"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        sizes = _axis_size_hints(ctx.tree)
        if not sizes:
            return  # axis sizes are runtime (device count): stay silent
        pspec = _pspec_aliases(ctx.tree)
        _, consts = _module_axis_decls(ctx.tree)
        proj_axes, proj_consts = declared_mesh_axes(ctx.path)
        merged = dict(proj_consts)
        merged.update(consts)
        for fi in ctx.index.functions.values():
            if not hasattr(fi.node, "body"):
                continue
            shapes: Dict[str, Tuple[int, ...]] = {}
            for stmt in flatten_statements(fi.node):
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call):
                    p = node_path(stmt.value.func)
                    if p in _UNCOMMITTED_ALLOCS or p in _HOST_ALLOCS:
                        sh = _literal_shape(stmt.value)
                        if sh is not None:
                            for t in stmt.targets:
                                for tp in target_paths(t):
                                    shapes[tp] = sh
                for expr in walk_exprs(stmt):
                    if isinstance(expr, ast.Call) and \
                            node_path(expr.func) in self._SINKS:
                        yield from self._check_sink(
                            ctx, fi, expr, shapes, sizes, pspec, merged)

    def _check_sink(self, ctx, fi, call, shapes, sizes, pspec,
                    consts) -> Iterator[Finding]:
        if len(call.args) < 2:
            return
        arr, sharding = call.args[0], call.args[1]
        shape: Optional[Tuple[int, ...]] = None
        if isinstance(arr, ast.Name):
            shape = shapes.get(arr.id)
        elif isinstance(arr, ast.Call):
            p = node_path(arr.func)
            if p in _UNCOMMITTED_ALLOCS or p in _HOST_ALLOCS:
                shape = _literal_shape(arr)
        if shape is None:
            return
        spec = self._find_pspec(sharding, pspec)
        if spec is None:
            return
        for i, arg in enumerate(spec.args):
            if i >= len(shape):
                break
            names: List[str] = []
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names = [arg.value]
            elif isinstance(arg, (ast.Name, ast.Attribute)):
                n = arg.id if isinstance(arg, ast.Name) else arg.attr
                if n in consts:
                    names = [consts[n]]
            elif isinstance(arg, (ast.Tuple, ast.List)):
                for e in arg.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        names.append(e.value)
            total = 1
            known = True
            for n in names:
                if n in sizes:
                    total *= sizes[n]
                else:
                    known = False
            if names and known and total > 1 and shape[i] % total != 0:
                yield self.finding(
                    ctx, arg,
                    f"dim {i} of shape {tuple(shape)} is sharded over "
                    f"axis {'+'.join(names)} of size {total} but "
                    f"{shape[i]} % {total} != 0 — GSPMD will pad or "
                    f"reshard silently", fi.qualname)

    @staticmethod
    def _find_pspec(node: ast.expr, pspec: Set[str]) -> Optional[ast.Call]:
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and node_path(n.func) in pspec:
                return n
        return None


class DonationAliasMismatchRule(Rule):
    id = "donation-alias-mismatch"
    severity = ERROR
    short = ("donate_argnums operand never flows into the traced "
             "function's results — the donated buffer cannot alias "
             "any output")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        by_qual = {fi.qualname: fi
                   for fi in ctx.index.functions.values()}
        for b in ctx.index.bindings:
            if not b.donate_argnums or not b.target_qualname:
                continue
            fi = by_qual.get(b.target_qualname)
            if fi is None or not hasattr(fi.node, "body"):
                continue
            params = fi.param_names()
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            for argnum in b.donate_argnums:
                if argnum >= len(params):
                    continue
                donor = params[argnum]
                if not self._reaches_return(fi, donor):
                    yield Finding(
                        rule=self.id, severity=self.severity,
                        path=ctx.path, line=b.lineno, col=1,
                        message=(
                            f"donate_argnums={argnum} donates "
                            f"`{donor}` to `{b.target_qualname}` but no "
                            f"return value derives from it; the buffer "
                            f"cannot be aliased to any output"),
                        func=b.target_qualname)

    @staticmethod
    def _reaches_return(fi, donor: str) -> bool:
        tainted: Set[str] = {donor}
        if isinstance(fi.node, ast.Lambda):
            return reads_tainted(fi.node.body, tainted)
        stmts = flatten_statements(fi.node)
        # fixpoint over the straight-lined body: loops/branches are
        # flattened, so two passes close simple forward chains
        for _ in range(2):
            for stmt in stmts:
                if isinstance(stmt, ast.Assign):
                    if reads_tainted(stmt.value, tainted):
                        for t in stmt.targets:
                            tainted.update(target_paths(t))
                elif isinstance(stmt, ast.AugAssign):
                    if reads_tainted(stmt.value, tainted) or \
                            reads_tainted(stmt.target, tainted):
                        tainted.update(target_paths(stmt.target))
        for stmt in stmts:
            if isinstance(stmt, ast.Return) and stmt.value is not None \
                    and reads_tainted(stmt.value, tainted):
                return True
        return False


class PlacementMixRule(Rule):
    id = "placement-mix"
    severity = ERROR
    short = ("traced code combines a committed (device_put) value with "
             "an uncommitted jnp allocation in one op")

    _COMMITTED_SRC = {"jax.device_put", "device_put"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fi in ctx.index.traced_functions():
            if not hasattr(fi.node, "body"):
                continue
            committed: Set[str] = set()
            uncommitted: Set[str] = set()
            for stmt in flatten_statements(fi.node):
                for expr in walk_exprs(stmt):
                    f = self._mix_at(expr, committed, uncommitted)
                    if f is not None:
                        yield self.finding(
                            ctx, f,
                            "committed (device_put) and uncommitted "
                            "(fresh jnp allocation) values meet in one "
                            "op inside traced code; the mixed layouts "
                            "compile a second executable — commit both "
                            "or neither (numpy inputs are neutral)",
                            fi.qualname)
                self._propagate(stmt, committed, uncommitted)

    def _placement_of_expr(self, expr: ast.expr, committed: Set[str],
                           uncommitted: Set[str]) -> Optional[str]:
        if isinstance(expr, ast.Call):
            p = node_path(expr.func)
            if p in self._COMMITTED_SRC:
                return "committed"
            if p in _UNCOMMITTED_ALLOCS:
                return "uncommitted"
            return None
        p = node_path(expr)
        if p is None:
            return None
        if p in committed:
            return "committed"
        if p in uncommitted:
            return "uncommitted"
        return None

    def _mix_at(self, expr: ast.AST, committed: Set[str],
                uncommitted: Set[str]) -> Optional[ast.AST]:
        operands: List[ast.expr] = []
        if isinstance(expr, ast.BinOp):
            operands = [expr.left, expr.right]
        elif isinstance(expr, ast.Call):
            p = node_path(expr.func) or ""
            if p.startswith(("jnp.", "jax.lax.", "jax.numpy.")):
                operands = list(expr.args)
        if len(operands) < 2:
            return None
        tags = {self._placement_of_expr(o, committed, uncommitted)
                for o in operands}
        if "committed" in tags and "uncommitted" in tags:
            return expr
        return None

    def _propagate(self, stmt: ast.stmt, committed: Set[str],
                   uncommitted: Set[str]) -> None:
        if not isinstance(stmt, ast.Assign):
            return
        tag = None
        v = stmt.value
        if isinstance(v, ast.Call):
            p = node_path(v.func)
            if p in self._COMMITTED_SRC:
                tag = "committed"
            elif p in _UNCOMMITTED_ALLOCS:
                tag = "uncommitted"
            elif p in _HOST_ALLOCS:
                tag = "neutral"
        if tag is None:
            if reads_tainted(v, committed):
                tag = "committed"
            elif reads_tainted(v, uncommitted):
                tag = "uncommitted"
        for t in stmt.targets:
            for tp in target_paths(t):
                committed.discard(tp)
                uncommitted.discard(tp)
                if tag == "committed":
                    committed.add(tp)
                elif tag == "uncommitted":
                    uncommitted.add(tp)


#: the ``--check`` tier catalog (separate from graftlint's ALL_RULES so
#: the lint tier's behaviour — and its pinned gate test — is unchanged)
SHARDING_RULES: List[Rule] = [
    MeshAxisUnknownRule(),
    ShardIndivisibleRule(),
    DonationAliasMismatchRule(),
    PlacementMixRule(),
]

#: every check-tier rule id, including the two produced by the
#: abstract interpreter rather than a per-module Rule object
CHECK_RULE_IDS: Set[str] = {r.id for r in SHARDING_RULES} | {
    "signature-escape", "unbounded-signature"}
