"""graftown rule catalog — the ``--tier own`` ownership rules.

Five path-sensitive rules over :mod:`.ownership`'s effect summaries and
exception-edge path walk, each the static form of a runtime guard the
repo already paid for once:

* ``leak-on-exception-path`` — a resource acquired locally can reach
  the function's exception exit still live (the ``check_invariants``
  "leaked slots" sweep, moved to CI time).
* ``double-release`` — a release reachable twice along one path (the
  PR-2 ``SlotPool`` double-free RuntimeError, now a static error).
* ``use-after-release`` — a released handle passed back into an
  effectful call of the same kind on the same path.
* ``unbalanced-refcount`` — a page acquired or ref'd whose refcount is
  neither dropped nor handed off on some path (the PR-7 trie/CoW
  ``consistency_errors`` class).
* ``missing-rollback`` — request-lifecycle state mutated under a
  ``try`` whose handler re-raises without restoring the field (the
  PR-6 snapshot-rollback design rule).

All five share one analysis pass, computed once per file and cached on
the :class:`~.rules.ModuleContext` (the ``get_thread_map`` pattern).
Suppressions use the house pragma with a mandatory reason::

    # graftlint: allow[leak-on-exception-path] -- ownership transferred
    #     to the retry queue two frames up
"""

from __future__ import annotations

from typing import Dict, Iterator, List

from .findings import ERROR, Finding
from .ownership import RawFinding, analyze_functions
from .rules import ModuleContext, Rule


def get_ownership(ctx: ModuleContext) -> Dict[str, List[RawFinding]]:
    """Raw graftown findings for ``ctx``, bucketed by rule id; computed
    once per file and cached on the context."""
    cached = getattr(ctx, "_ownership", None)
    if cached is None:
        cached = {}
        for rf in analyze_functions(ctx.index):
            cached.setdefault(rf.rule, []).append(rf)
        ctx._ownership = cached
    return cached


class _OwnRule(Rule):
    severity = ERROR

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for rf in get_ownership(ctx).get(self.id, ()):
            yield self.finding(ctx, rf.node, rf.message,
                               func=rf.fi.qualname)


class LeakOnExceptionPathRule(_OwnRule):
    id = "leak-on-exception-path"
    short = ("resource acquired, then an escaping raise path reaches "
             "the function exit without the matching release")


class DoubleReleaseRule(_OwnRule):
    id = "double-release"
    short = ("release reachable twice along one path (static form of "
             "the runtime double-free guard)")


class UseAfterReleaseRule(_OwnRule):
    id = "use-after-release"
    short = ("released slot/page handle passed back into an effectful "
             "call on the same path")


class UnbalancedRefcountRule(_OwnRule):
    id = "unbalanced-refcount"
    short = ("page ref/alloc with no unref or ownership handoff on "
             "some path through the function")


class MissingRollbackRule(_OwnRule):
    id = "missing-rollback"
    short = ("request state mutated under a try whose handler "
             "re-raises without restoring the field")


OWN_RULES = (
    LeakOnExceptionPathRule(),
    DoubleReleaseRule(),
    UseAfterReleaseRule(),
    UnbalancedRefcountRule(),
    MissingRollbackRule(),
)

OWN_RULE_IDS = {r.id for r in OWN_RULES}
