"""graftown — static ownership & exception-path resource analysis.

The serving stack's costliest runtime failures are lifecycle bugs: a
slot allocated and never released on a raise path, a page refcount that
drifts, state mutated under a ``try`` whose handler forgets to roll it
back.  Today those are caught (late) by ``check_invariants()`` /
``consistency_errors()`` sweeps and chaos tests; graftown proves the
same class of invariant *statically*, before anything runs, the way
graftlint did for trace safety and graftsync for thread contexts.

Three layers, all stdlib ``ast`` over :class:`~.dataflow.ModuleIndex`
(no jax import — the tier must gate CI in milliseconds):

* :data:`EFFECT_TABLE` — a declarative catalog of the repo's resource
  primitives: which method names acquire, release, ref/unref or
  transfer each resource *kind* (slot, page, seat, future, lock).  Add
  a kind by adding a table entry plus a :data:`RUNTIME_AUDIT` pointer
  to its runtime sweep (a drift test pins both directions).
* :class:`EffectMap` — per-function resource-effect summaries inferred
  from the table and propagated transitively through helper calls to a
  fixpoint (``_evict_slot(req)`` *releases* ``req.slot``, so every
  caller of ``_evict_slot`` inherits that release).  ``--effects``
  dumps the result as reproducible JSON.
* :func:`analyze_functions` — a bounded path-sensitive walk of each
  function's control flow **including exception edges**: every
  may-raise call site forks an exception edge to the innermost
  ``except``/``finally`` (or the function's exception exit), ``If``
  arms fork with condition memoisation (two ``if cond:`` guards with
  the same test take the same arm on one path, which is what keeps
  "conditional acquire matched by the same-condition release" silent),
  loops run zero-or-once.  The walk tracks handle states
  (live/released/escaped) and emits the raw findings behind the five
  graftown rules (catalog: :mod:`.ownership_rules`).

Modeling choices (deliberate, documented so triage stays explainable):

* Release-category calls (``release``/``unref_page``/``set_result``)
  are modeled as non-raising: their runtime guards raise only on the
  misuse (double free) that the static tier flags directly, and
  treating them as may-raise would flag every rollback handler.
* ``assert``, ``del``, subscript reads and a small safe-call whitelist
  (``len``, ``dict.get``, ``list.append``, ...) are non-raising;
  every other call may raise.
* Container sinks (``.put``/``.append``/``.add``/...) are also
  non-raising: a handoff into an in-process container failing *between*
  acquire and enqueue is not a realistic leak class (unbounded
  ``queue.put`` never raises), and modeling it flags every
  future-then-enqueue bridge idiom.
* A handle *escapes* (tracking stops) when stored into an attribute,
  container or subscript target, passed to a container sink
  (``.put``/``.append``/...), passed to a transfer-category call (the
  prefix-trie handoff), returned, or passed to a helper whose summary
  transfers it.  A plain pass-as-argument is NOT an escape.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field, replace
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .dataflow import FuncInfo, FunctionNode, ModuleIndex, node_path

# ------------------------------------------------------------ effect table

#: resource kind -> effect category -> method names.  The names are the
#: repo's primitives (SlotPool / PagedKVPool / PrefixCache / scheduler /
#: bridge); receiver heuristics disambiguate collisions (see
#: :func:`classify_call`).
EFFECT_TABLE: Dict[str, Dict[str, Tuple[str, ...]]] = {
    "slot": {
        "acquire": ("alloc",),
        "release": ("release",),
        "release_all": ("reset",),
        "use": ("admit", "admit_rows", "advance", "reset_row",
                "ensure_writable", "seat_prefix", "map_prefix",
                "cache_prefix", "run_prefill_chunk"),
    },
    "page": {
        # import_pages: the cross-pool transfer primitive — destination
        # pages come back refcount-1 OWNED BY THE CALLER (exactly like
        # alloc_page) until seat_pages moves them into a slot table
        "acquire": ("alloc_page", "import_pages"),
        "ref": ("ref_page",),
        "unref": ("unref_page", "unref_pages"),
        "transfer": ("insert", "map_prefix", "seat_pages", "seat_prefix"),
    },
    "seat": {
        "acquire": ("grant",),
        "release": ("requeue_front", "requeue_back", "expire"),
        "use": ("submit",),
    },
    "future": {
        "acquire": ("create_future",),
        "release": ("set_result", "set_exception"),
    },
    "lock": {
        "acquire": ("acquire",),
        "release": ("release",),
    },
}

#: kinds whose handles the path walk tracks.  ``seat`` is inventory-only:
#: ``grant()`` returns a *batch* whose choreography (requeue vs admit vs
#: abort) is the engine's step contract, audited at runtime by
#: ``check_invariants`` — per-handle tracking would only produce noise.
TRACKED_KINDS = frozenset({"slot", "page", "future", "lock"})

#: static kind -> the runtime audit(s) covering the same resource, as
#: ``Class.method`` names in ``deepspeed_tpu/serving``.  The inventory
#: test pins BOTH directions: every kind has an entry here, and every
#: runtime ``check_invariants``/``consistency_errors`` definition is
#: claimed by some kind — a new pool resource cannot silently skip the
#: static tier.  ``lock`` has no runtime sweep (with-statement
#: balancing is by construction); the static tier is its only auditor.
RUNTIME_AUDIT: Dict[str, Tuple[str, ...]] = {
    "slot": ("SlotPool.consistency_errors",
             "ServingEngine.check_invariants"),
    "page": ("PagedKVPool.consistency_errors",),
    "seat": ("ServingEngine.check_invariants",
             "ReplicaRouter.check_invariants"),
    "future": ("AsyncEngineBridge._reject_pending_ops",),
    "lock": (),
}

#: receiver-path components that mark a ``.acquire()``/``.release()``
#: pair as a lock, not a slot (``self._lock.release()`` vs
#: ``self.pool.release(slot)``)
_LOCKISH = ("lock", "cond", "sem", "mutex")

#: calls modeled as non-raising (see module docstring)
_SAFE_BUILTINS = frozenset({
    "len", "int", "float", "bool", "str", "repr", "min", "max", "abs",
    "sum", "any", "all", "sorted", "list", "tuple", "dict", "set",
    "frozenset", "enumerate", "zip", "range", "reversed", "isinstance",
    "issubclass", "getattr", "hasattr", "id", "print", "format",
    "round", "callable", "iter", "next", "vars", "type",
})
_SAFE_METHODS = frozenset({
    "append", "appendleft", "extend", "add", "discard", "clear",
    "copy", "get", "items", "keys", "values", "update", "setdefault",
    "count", "index", "join", "split", "strip", "startswith",
    "endswith", "is_alive", "is_set", "time", "monotonic",
    "perf_counter", "debug", "info", "warning", "error",
})
#: method names whose arguments land in a container the caller no
#: longer owns — passing a handle here is an ownership handoff
_SINK_METHODS = frozenset({
    "put", "put_nowait", "append", "appendleft", "add", "insert",
    "extend", "push", "setdefault", "update",
})

#: request-lifecycle fields the missing-rollback rule tracks: mutated
#: under a ``try`` whose handler re-raises, they must be restored (any
#: assignment to the same field in handler or ``finally``) before the
#: exception escapes — the PR-6 snapshot-rollback design rule
ROLLBACK_FIELDS = frozenset({"state", "slot", "prefill_pos",
                             "admit_time", "first_token_time"})

# handle states
LIVE = "live"
RELEASED = "released"
ESCAPED = "escaped"

#: per-function path budget; beyond it forks stop and exit-based
#: findings on the truncated paths are dropped (site-based findings
#: already emitted are kept)
MAX_PATHS = 2048


# ------------------------------------------------------- call classification

#: method name -> [(kind, category)] built from the table
_METHOD_EFFECTS: Dict[str, List[Tuple[str, str]]] = {}
for _kind, _cats in EFFECT_TABLE.items():
    for _cat, _names in _cats.items():
        for _n in _names:
            _METHOD_EFFECTS.setdefault(_n, []).append((_kind, _cat))


def _is_lockish(path: Optional[str]) -> bool:
    if not path:
        return False
    low = path.lower()
    return any(k in low for k in _LOCKISH)


def classify_call(call: ast.Call) -> Optional[Tuple[str, str, str]]:
    """``(kind, category, method)`` for an effect-table call, else None.

    Collisions resolve on the receiver: ``release``/``acquire`` on a
    lock-like path (``self._lock``) are the lock kind; ``acquire`` on
    anything else is unclassified (only locks acquire in place);
    ``release`` on anything else is the slot kind.
    """
    if not isinstance(call.func, ast.Attribute):
        return None
    method = call.func.attr
    cands = _METHOD_EFFECTS.get(method)
    if not cands:
        return None
    recv = node_path(call.func.value)
    lockish = _is_lockish(recv)
    for kind, cat in cands:
        if kind == "lock":
            if lockish:
                return (kind, cat, method)
            continue
        if lockish:
            continue
        return (kind, cat, method)
    return None


def _handle_on_receiver(kind: str) -> bool:
    """Locks and futures carry the effect on the receiver
    (``lock.release()``); slots and pages pass the handle as the first
    argument (``pool.release(slot)``)."""
    return kind in ("lock", "future")


# ------------------------------------------------------ function summaries

@dataclass
class FuncSummary:
    """Transitive resource effects of calling one function."""
    fi: FuncInfo
    #: ``(param index, attr chain)`` paths released by a call
    releases: Set[Tuple[int, Tuple[str, ...]]] = field(default_factory=set)
    #: param indices whose argument escapes into storage
    transfers: Set[int] = field(default_factory=set)
    #: kind of a fresh handle this function returns, if any
    acquires: Optional[str] = None
    may_raise: bool = False

    def nontrivial(self) -> bool:
        return bool(self.releases or self.transfers or self.acquires)

    def to_dict(self) -> Dict[str, object]:
        return {
            "acquires": self.acquires,
            "may_raise": self.may_raise,
            "releases": sorted(
                "arg%d%s" % (i, "".join("." + a for a in attrs))
                for i, attrs in self.releases),
            "transfers": sorted("arg%d" % i for i in self.transfers),
        }


def _own_stmts(fn: ast.AST) -> Iterator[ast.stmt]:
    """Statements of ``fn`` recursively, without entering nested
    function/class definitions."""
    def rec(stmts: Sequence[ast.stmt]) -> Iterator[ast.stmt]:
        for s in stmts:
            yield s
            if isinstance(s, FunctionNode + (ast.ClassDef,)):
                continue
            for fname in ("body", "orelse", "finalbody"):
                v = getattr(s, fname, None)
                if isinstance(v, list):
                    yield from rec(v)
            for h in getattr(s, "handlers", []) or []:
                yield from rec(h.body)
    yield from rec(getattr(fn, "body", []))


def _expr_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Calls owned by ``stmt`` (not those of nested statements), in walk
    order; descends into comprehensions but not lambdas.  Memoised on
    the statement node — the path walk and the raise oracle both ask
    for the same statement's calls many times over."""
    cached = getattr(stmt, "_own_expr_calls", None)
    if cached is not None:
        return cached
    from .dataflow import stmt_exprs
    out: List[ast.Call] = []
    for e in stmt_exprs(stmt):
        for n in ast.walk(e):
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
    stmt._own_expr_calls = out
    return out


class EffectMap:
    """Per-function :class:`FuncSummary` for one module, inferred from
    :data:`EFFECT_TABLE` and propagated through direct calls (bare
    name / ``self.method()``) to a fixpoint."""

    def __init__(self, index: ModuleIndex):
        self.index = index
        self.summaries: Dict[ast.AST, FuncSummary] = {}
        self._by_name_module = {
            fi.node.name: fi for fi in index.functions.values()
            if fi.parent is None and isinstance(fi.node, FunctionNode)}
        self._methods: Dict[Tuple[str, str], FuncInfo] = {}
        for fi in index.functions.values():
            if fi.class_name and isinstance(fi.node, FunctionNode):
                self._methods[(fi.class_name, fi.node.name)] = fi
        for fi in index.functions.values():
            if isinstance(fi.node, FunctionNode):
                self.summaries[fi.node] = FuncSummary(fi)
        changed = True
        iters = 0
        while changed and iters < 20:
            changed = False
            iters += 1
            for fi in self.index.functions.values():
                if isinstance(fi.node, FunctionNode):
                    if self._summarize(fi):
                        changed = True

    # -------------------------------------------------------- resolution
    def resolve_callee(self, call: ast.Call, fi: FuncInfo
                       ) -> Optional[FuncInfo]:
        return self.index._resolve_callee(
            call.func, fi, {}, self._by_name_module, self._methods)

    def callee_summary(self, call: ast.Call, fi: FuncInfo
                       ) -> Optional[FuncSummary]:
        cal = self.resolve_callee(call, fi)
        if cal is None:
            return None
        return self.summaries.get(cal.node)

    @staticmethod
    def arg_for_param(call: ast.Call, cal: FuncInfo, pidx: int
                      ) -> Optional[ast.expr]:
        """The call-site expression bound to the callee's ``pidx``-th
        parameter, adjusting for the bound receiver of
        ``self.method(...)`` calls."""
        names = cal.param_names()
        if pidx >= len(names):
            return None
        offset = 0
        if cal.class_name and names and names[0] in ("self", "cls") \
                and isinstance(call.func, ast.Attribute):
            offset = 1
        if pidx == 0 and offset == 1:
            return call.func.value      # the receiver itself
        k = pidx - offset
        if 0 <= k < len(call.args):
            a = call.args[k]
            return None if isinstance(a, ast.Starred) else a
        for kw in call.keywords:
            if kw.arg == names[pidx]:
                return kw.value
        return None

    # ----------------------------------------------------- summarization
    def call_may_raise(self, call: ast.Call, fi: FuncInfo) -> bool:
        """May-raise model for one call site (see module docstring)."""
        eff = classify_call(call)
        if eff is not None and eff[1] in ("release", "unref",
                                          "release_all"):
            return False
        if isinstance(call.func, ast.Name):
            if call.func.id in _SAFE_BUILTINS:
                return False
        elif isinstance(call.func, ast.Attribute):
            m = call.func.attr
            if m in _SAFE_METHODS:
                return False
            if m in _SINK_METHODS:
                return False            # container handoff (see docstring)
            if m == "pop" and len(call.args) >= 2:
                return False            # dict.pop(key, default)
        cal = self.resolve_callee(call, fi)
        if cal is not None:
            summ = self.summaries.get(cal.node)
            if summ is not None:
                return summ.may_raise
        return True

    def stmt_may_raise(self, stmt: ast.stmt, fi: FuncInfo) -> bool:
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, (ast.Assert, ast.Delete)):
            return False
        return any(self.call_may_raise(c, fi)
                   for c in _expr_calls(stmt))

    def _summarize(self, fi: FuncInfo) -> bool:
        """One summarization pass over ``fi``; True when the summary
        grew (drives the fixpoint)."""
        summ = self.summaries[fi.node]
        params = fi.param_names()
        pidx_of = {n: i for i, n in enumerate(params)}
        # local name -> param-rooted dotted path ("slot" -> "req.slot")
        alias: Dict[str, str] = {n: n for n in params}
        releases: Set[Tuple[int, Tuple[str, ...]]] = set()
        transfers: Set[int] = set()
        acquires: Optional[str] = None
        may_raise = False
        acquired_locals: Dict[str, str] = {}   # name -> kind

        def resolve_path(expr: ast.expr) -> Optional[str]:
            p = node_path(expr)
            if p is None:
                return None
            head, _, rest = p.partition(".")
            head = alias.get(head, head)
            return head + ("." + rest if rest else "")

        def param_key(path: Optional[str]
                      ) -> Optional[Tuple[int, Tuple[str, ...]]]:
            if not path:
                return None
            parts = path.split(".")
            if parts[0] not in pidx_of:
                return None
            return (pidx_of[parts[0]], tuple(parts[1:]))

        for stmt in _own_stmts(fi.node):
            if isinstance(stmt, FunctionNode + (ast.ClassDef,)):
                continue
            if not may_raise and self.stmt_may_raise(stmt, fi):
                may_raise = True
            for call in _expr_calls(stmt):
                eff = classify_call(call)
                if eff is not None:
                    kind, cat, _m = eff
                    if cat in ("release", "unref") and \
                            kind in TRACKED_KINDS:
                        if _handle_on_receiver(kind):
                            operand: Optional[ast.expr] = call.func.value
                        else:
                            operand = call.args[0] if call.args else None
                        key = param_key(resolve_path(operand)
                                        if operand is not None else None)
                        if key is not None:
                            releases.add(key)
                    if cat == "transfer":
                        for a in call.args:
                            key = param_key(resolve_path(a))
                            if key is not None and not key[1]:
                                transfers.add(key[0])
                elif isinstance(call.func, ast.Attribute) and \
                        call.func.attr in _SINK_METHODS:
                    for a in call.args:
                        key = param_key(resolve_path(a))
                        if key is not None and not key[1]:
                            transfers.add(key[0])
                # transitive: helper summaries
                cal = self.resolve_callee(call, fi)
                if cal is not None:
                    csum = self.summaries.get(cal.node)
                    if csum is None:
                        continue
                    for pidx, attrs in csum.releases:
                        arg = self.arg_for_param(call, cal, pidx)
                        if arg is None:
                            continue
                        path = resolve_path(arg)
                        key = param_key(
                            (path + "." + ".".join(attrs)) if attrs
                            else path) if path else None
                        if key is not None:
                            releases.add(key)
                    for pidx in csum.transfers:
                        arg = self.arg_for_param(call, cal, pidx)
                        if arg is not None:
                            key = param_key(resolve_path(arg))
                            if key is not None and not key[1]:
                                transfers.add(key[0])
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                val_path = resolve_path(stmt.value)
                if val_path is not None:
                    alias[name] = val_path
                else:
                    alias.pop(name, None)
                if isinstance(stmt.value, ast.Call):
                    eff = classify_call(stmt.value)
                    if eff and eff[1] == "acquire" and \
                            eff[0] in TRACKED_KINDS:
                        acquired_locals[name] = eff[0]
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                if isinstance(stmt.value, ast.Call):
                    eff = classify_call(stmt.value)
                    if eff and eff[1] == "acquire" and \
                            eff[0] in TRACKED_KINDS:
                        acquires = eff[0]
                elif isinstance(stmt.value, ast.Name) and \
                        stmt.value.id in acquired_locals:
                    acquires = acquired_locals[stmt.value.id]

        grew = (not releases <= summ.releases
                or not transfers <= summ.transfers
                or (acquires is not None and summ.acquires is None)
                or (may_raise and not summ.may_raise))
        summ.releases |= releases
        summ.transfers |= transfers
        summ.acquires = summ.acquires or acquires
        summ.may_raise = summ.may_raise or may_raise
        return grew

    # ----------------------------------------------------------- export
    def labels(self) -> Dict[str, Dict[str, object]]:
        """``qualname -> summary`` for every function with a nontrivial
        resource effect — deterministic, the ``--effects`` payload."""
        out: Dict[str, Dict[str, object]] = {}
        for summ in self.summaries.values():
            if summ.nontrivial():
                out[summ.fi.qualname] = summ.to_dict()
        return dict(sorted(out.items()))


def effect_table_dict() -> Dict[str, Dict[str, List[str]]]:
    """The declarative table as sorted JSON-able dict (``--effects``)."""
    return {k: {c: sorted(n) for c, n in sorted(cats.items())}
            for k, cats in sorted(EFFECT_TABLE.items())}


# ------------------------------------------------------------ path analysis

@dataclass
class Handle:
    kind: str
    state: str
    node: ast.AST               # acquire site (or first release site for
    path: Optional[str] = None  # param-rooted path handles)
    implicit: bool = False      # created by releasing a path we never
    #                             saw acquired (double-release tracking)


class _State:
    """One path's view: handle table, name/path bindings, memoized
    branch conditions."""

    __slots__ = ("handles", "bindings", "paths", "aliases", "conds")

    def __init__(self) -> None:
        self.handles: Dict[int, Handle] = {}
        self.bindings: Dict[str, int] = {}   # local name -> handle id
        self.paths: Dict[str, int] = {}      # dotted path -> handle id
        self.aliases: Dict[str, str] = {}    # local name -> dotted path
        self.conds: Dict[str, bool] = {}     # ast.dump(test) -> branch

    def clone(self) -> "_State":
        st = _State.__new__(_State)
        st.handles = {k: replace(v) for k, v in self.handles.items()}
        st.bindings = dict(self.bindings)
        st.paths = dict(self.paths)
        st.aliases = dict(self.aliases)
        st.conds = dict(self.conds)
        return st

    def sig(self) -> Tuple:
        return (tuple(sorted((k, v.state) for k, v in
                             self.handles.items())),
                tuple(sorted(self.bindings.items())),
                tuple(sorted(self.paths.items())))


@dataclass
class Outcome:
    kind: str                   # "fall" | "return" | "raise" | "break"
    state: _State               # | "continue" | "abandon"
    origin: Optional[ast.AST] = None


@dataclass
class RawFinding:
    rule: str
    node: ast.AST
    message: str
    fi: FuncInfo


class _Walker:
    """Bounded path-sensitive walk of one function (see module
    docstring for the modeling rules)."""

    def __init__(self, fi: FuncInfo, emap: EffectMap):
        self.fi = fi
        self.emap = emap
        self.findings: List[RawFinding] = []
        self._emitted: Set[Tuple[str, int]] = set()
        self._next_handle = 1
        self._budget = MAX_PATHS
        self._cond_names: Dict[str, Set[str]] = {}

    # ------------------------------------------------------------ emit
    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        key = (rule, getattr(node, "lineno", 0))
        if key not in self._emitted:
            self._emitted.add(key)
            self.findings.append(RawFinding(rule, node, message, self.fi))

    # ------------------------------------------------------- resolution
    def _resolve_path(self, expr: ast.expr, st: _State) -> Optional[str]:
        p = node_path(expr)
        if p is None:
            return None
        head, _, rest = p.partition(".")
        head = st.aliases.get(head, head)
        return head + ("." + rest if rest else "")

    def _handle_for(self, expr: ast.expr, st: _State) -> Optional[Handle]:
        if isinstance(expr, ast.Name) and expr.id in st.bindings:
            return st.handles.get(st.bindings[expr.id])
        path = self._resolve_path(expr, st)
        if path is not None and path in st.paths:
            return st.handles.get(st.paths[path])
        return None

    def _new_handle(self, kind: str, state: str, node: ast.AST,
                    st: _State, path: Optional[str] = None,
                    implicit: bool = False) -> int:
        hid = self._next_handle
        self._next_handle += 1
        st.handles[hid] = Handle(kind, state, node, path, implicit)
        if path is not None:
            st.paths[path] = hid
        return hid

    # ------------------------------------------------------ call events
    def _release_event(self, call: ast.Call, operand: Optional[ast.expr],
                       kind: str, st: _State) -> None:
        h = self._handle_for(operand, st) if operand is not None else None
        if h is not None:
            if h.state == RELEASED:
                self._emit(
                    "double-release", call,
                    f"{h.kind} handle released twice on one path "
                    f"(first release survives from line "
                    f"{getattr(h.node, 'lineno', '?')}) — generalizes "
                    f"the runtime double-free guard to a static error")
            elif h.state == LIVE:
                h.state = RELEASED
                h.node = call
            return
        if operand is None:
            return
        path = self._resolve_path(operand, st)
        if path is not None:
            # releasing a path we never saw acquired: start tracking so
            # a second release of the same path is a definite double
            self._new_handle(kind, RELEASED, call, st, path=path,
                             implicit=True)

    def _use_event(self, call: ast.Call, kind: str, st: _State) -> None:
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            h = self._handle_for(a, st)
            if h is not None and h.kind == kind and h.state == RELEASED:
                self._emit(
                    "use-after-release", call,
                    f"{kind} handle passed to effectful call after its "
                    f"release on this path (released at line "
                    f"{getattr(h.node, 'lineno', '?')})")

    def _escape(self, expr: ast.expr, st: _State) -> None:
        for n in ast.walk(expr):
            if isinstance(n, (ast.Name, ast.Attribute)):
                h = self._handle_for(n, st)
                if h is not None:
                    h.state = ESCAPED

    def _process_call(self, call: ast.Call, st: _State) -> None:
        eff = classify_call(call)
        if eff is not None:
            kind, cat, _m = eff
            if kind in TRACKED_KINDS:
                if cat in ("release", "unref"):
                    if _handle_on_receiver(kind):
                        self._release_event(call, call.func.value, kind,
                                            st)
                    else:
                        self._release_event(
                            call, call.args[0] if call.args else None,
                            kind, st)
                elif cat == "ref" and call.args:
                    # ref_page(pid): the +1 starts a tracked handle on
                    # the operand path; unref or handoff balances it
                    path = self._resolve_path(call.args[0], st)
                    if path is not None and path not in st.paths:
                        self._new_handle(kind, LIVE, call, st, path=path)
                elif cat == "use":
                    self._use_event(call, kind, st)
                elif cat == "transfer":
                    for a in call.args:
                        self._escape(a, st)
            elif cat == "use":
                self._use_event(call, kind, st)
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _SINK_METHODS:
            for a in call.args:
                self._escape(a, st)
        # transitive helper effects
        cal = self.emap.resolve_callee(call, self.fi)
        if cal is not None:
            summ = self.emap.summaries.get(cal.node)
            if summ is not None:
                for pidx, attrs in summ.releases:
                    arg = self.emap.arg_for_param(call, cal, pidx)
                    if arg is None:
                        continue
                    base = self._resolve_path(arg, st)
                    if base is None:
                        continue
                    path = ".".join((base,) + attrs) if attrs else base
                    hid = st.paths.get(path)
                    h = st.handles.get(hid) if hid is not None else None
                    if h is not None and h.state == RELEASED:
                        self._emit(
                            "double-release", call,
                            f"helper call releases `{path}` again on "
                            f"this path (first release survives from "
                            f"line {getattr(h.node, 'lineno', '?')})")
                    elif h is not None and h.state == LIVE:
                        h.state = RELEASED
                        h.node = call
                    elif h is None:
                        self._new_handle("slot", RELEASED, call, st,
                                         path=path, implicit=True)
                for pidx in summ.transfers:
                    arg = self.emap.arg_for_param(call, cal, pidx)
                    if arg is not None:
                        self._escape(arg, st)

    # -------------------------------------------------------- statements
    def _clear_path(self, path: str, st: _State) -> None:
        """An assignment to ``path`` rebinds it: drop path tracking for
        it and anything beneath it."""
        for p in [p for p in st.paths
                  if p == path or p.startswith(path + ".")]:
            st.paths.pop(p, None)
        for c in [c for c, names in list(self._cond_names.items())
                  if path.split(".")[0] in names]:
            st.conds.pop(c, None)

    def _assign(self, stmt: ast.stmt, st: _State) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        for c in _expr_calls(stmt):
            self._process_call(c, st)
        hid: Optional[int] = None
        val_path: Optional[str] = None
        if isinstance(value, ast.Call):
            eff = classify_call(value)
            if eff and eff[1] == "acquire" and eff[0] in TRACKED_KINDS:
                hid = self._new_handle(eff[0], LIVE, value, st)
            else:
                cal = self.emap.resolve_callee(value, self.fi)
                summ = self.emap.summaries.get(cal.node) \
                    if cal is not None else None
                if summ is not None and summ.acquires:
                    hid = self._new_handle(summ.acquires, LIVE, value, st)
        elif value is not None:
            if isinstance(value, ast.Name) and value.id in st.bindings:
                hid = st.bindings[value.id]
            val_path = self._resolve_path(value, st)
        for t in targets:
            flat = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for el in flat:
                if isinstance(el, ast.Starred):
                    el = el.value
                if isinstance(el, ast.Name):
                    st.bindings.pop(el.id, None)
                    st.aliases.pop(el.id, None)
                    self._clear_path(el.id, st)
                    if hid is not None and len(flat) == 1:
                        st.bindings[el.id] = hid
                    elif val_path is not None and len(flat) == 1:
                        st.aliases[el.id] = val_path
                else:
                    # attribute / subscript target: the stored value (and
                    # any subscript key) escapes; the target path rebinds.
                    # A fresh acquire stored straight into a container
                    # (``slots[i] = pool.alloc()``) escapes the same way.
                    if hid is not None:
                        st.handles[hid].state = ESCAPED
                    if value is not None:
                        self._escape(value, st)
                    if isinstance(el, ast.Subscript):
                        self._escape(el.slice, st)
                        tp = self._resolve_path(el.value, st)
                    else:
                        tp = self._resolve_path(el, st)
                    if tp is not None:
                        self._clear_path(tp, st)

    def _leak_check(self, st: _State, origin: ast.AST) -> None:
        for h in st.handles.values():
            if h.state == LIVE and not h.implicit:
                self._emit(
                    "leak-on-exception-path", h.node,
                    f"{h.kind} handle acquired here leaks when line "
                    f"{getattr(origin, 'lineno', '?')} raises: the "
                    f"exception escapes the function with no "
                    f"except/finally releasing it on that path")

    # ------------------------------------------------------ control flow
    def walk_function(self) -> None:
        st = _State()
        outs = self._walk_seq(list(self.fi.node.body), st, trap=None)
        for o in outs:
            if o.kind == "raise":
                self._leak_check(o.state, o.origin or self.fi.node)
            if o.kind in ("fall", "return"):
                for h in o.state.handles.values():
                    if h.state == LIVE and not h.implicit and \
                            h.kind == "page":
                        self._emit(
                            "unbalanced-refcount", h.node,
                            "page acquired/ref'd here is neither "
                            "unref'd nor handed off on some path "
                            "through the function — the refcount "
                            "drifts by +1")

    def _walk_seq(self, stmts: List[ast.stmt], st: _State,
                  trap: Optional[List[Tuple[_State, ast.AST]]]
                  ) -> List[Outcome]:
        """Walk ``stmts`` from state ``st``.  ``trap`` collects
        (pre-statement state, origin) snapshots at may-raise sites when
        inside a ``try`` body; outside any try a may-raise site is an
        exception edge straight to the function's exception exit, so
        live handles are leak-checked on the spot."""
        if self._budget <= 0:
            return [Outcome("abandon", st)]
        out: List[Outcome] = []
        states = [st]
        for i, stmt in enumerate(stmts):
            nxt: List[_State] = []
            for s in states:
                self._budget -= 1
                if self._budget <= 0:
                    out.append(Outcome("abandon", s))
                    continue
                if self.emap.stmt_may_raise(stmt, self.fi) and \
                        not isinstance(stmt, ast.Raise):
                    if trap is not None:
                        trap.append((s.clone(), stmt))
                    else:
                        self._leak_check(s, stmt)
                for o in self._walk_stmt(stmt, s, trap):
                    if o.kind == "fall":
                        nxt.append(o.state)
                    else:
                        out.append(o)
            states = nxt
            if not states:
                return out
        out.extend(Outcome("fall", s) for s in states)
        return out

    def _walk_stmt(self, stmt: ast.stmt, st: _State,
                   trap: Optional[List[Tuple[_State, ast.AST]]]
                   ) -> List[Outcome]:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt, ast.AugAssign) or \
                    (isinstance(stmt, ast.AnnAssign)
                     and stmt.value is None):
                for c in _expr_calls(stmt):
                    self._process_call(c, st)
            else:
                self._assign(stmt, st)
            return [Outcome("fall", st)]
        if isinstance(stmt, ast.Return):
            for c in _expr_calls(stmt):
                self._process_call(c, st)
            if stmt.value is not None:
                if isinstance(stmt.value, ast.Call):
                    eff = classify_call(stmt.value)
                    if not (eff and eff[1] == "acquire"):
                        self._escape(stmt.value, st)
                else:
                    self._escape(stmt.value, st)
            return [Outcome("return", st)]
        if isinstance(stmt, ast.Raise):
            for c in _expr_calls(stmt):
                self._process_call(c, st)
            return [Outcome("raise", st, stmt)]
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return [Outcome("break" if isinstance(stmt, ast.Break)
                            else "continue", st)]
        if isinstance(stmt, ast.If):
            return self._walk_if(stmt, st, trap)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._walk_loop(stmt, st, trap)
        if isinstance(stmt, ast.Try):
            return self._walk_try(stmt, st, trap)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                for n in ast.walk(item.context_expr):
                    if isinstance(n, ast.Call):
                        self._process_call(n, st)
            return self._walk_seq(list(stmt.body), st, trap)
        if isinstance(stmt, FunctionNode + (ast.ClassDef,)):
            return [Outcome("fall", st)]
        for c in _expr_calls(stmt):
            self._process_call(c, st)
        return [Outcome("fall", st)]

    def _walk_if(self, stmt: ast.If, st: _State,
                 trap) -> List[Outcome]:
        key = ast.dump(stmt.test)
        names = {n.id for n in ast.walk(stmt.test)
                 if isinstance(n, ast.Name)}
        self._cond_names[key] = names
        for c in _expr_calls(ast.Expr(value=stmt.test)):
            self._process_call(c, st)
        if key in st.conds:
            branch = stmt.body if st.conds[key] else stmt.orelse
            return self._walk_seq(list(branch), st, trap)
        out: List[Outcome] = []
        st2 = st.clone()
        st.conds[key] = True
        st2.conds[key] = False
        out.extend(self._walk_seq(list(stmt.body), st, trap))
        out.extend(self._walk_seq(list(stmt.orelse), st2, trap))
        return out

    def _walk_loop(self, stmt, st: _State, trap) -> List[Outcome]:
        """Loops run zero-or-once; ``while True`` cannot run zero times
        and a fall off the end of its single modeled iteration abandons
        the path (no exit exists to check)."""
        infinite = isinstance(stmt, ast.While) and \
            isinstance(stmt.test, ast.Constant) and stmt.test.value
        for c in _expr_calls(ast.Expr(value=getattr(
                stmt, "test", None) or getattr(stmt, "iter", None))):
            self._process_call(c, st)
        out: List[Outcome] = []
        body_st = st.clone() if not infinite else st
        if not infinite:
            out.extend(self._walk_seq(list(stmt.orelse), st, trap)
                       if stmt.orelse else [Outcome("fall", st)])
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._assign_loop_target(stmt.target, body_st)
        for o in self._walk_seq(list(stmt.body), body_st, trap):
            if o.kind in ("break", "continue", "fall"):
                if infinite and o.kind in ("continue", "fall"):
                    out.append(Outcome("abandon", o.state))
                else:
                    out.append(Outcome("fall", o.state))
            else:
                out.append(o)
        return out

    def _assign_loop_target(self, target: ast.expr, st: _State) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                st.bindings.pop(n.id, None)
                st.aliases.pop(n.id, None)
                self._clear_path(n.id, st)

    def _walk_try(self, stmt: ast.Try, st: _State, trap) -> List[Outcome]:
        inner: List[Tuple[_State, ast.AST]] = []
        body_outs = self._walk_seq(list(stmt.body), st, inner)
        falls = [o for o in body_outs if o.kind == "fall"]
        raises = [o for o in body_outs if o.kind == "raise"]
        others = [o for o in body_outs
                  if o.kind not in ("fall", "raise")]

        entry_states: List[Tuple[_State, Optional[ast.AST]]] = []
        seen: Set[Tuple] = set()
        for s, origin in inner:
            sg = s.sig()
            if sg not in seen:
                seen.add(sg)
                entry_states.append((s, origin))
        for o in raises:
            sg = o.state.sig()
            if sg not in seen:
                seen.add(sg)
                entry_states.append((o.state, o.origin))

        out: List[Outcome] = []
        if stmt.handlers and entry_states:
            for s, origin in entry_states:
                for h in stmt.handlers:
                    hs = s.clone()
                    houts = self._walk_seq(list(h.body), hs, trap)
                    for o in houts:
                        if o.kind == "raise" and o.origin is not None \
                                and isinstance(o.origin, ast.Raise) \
                                and o.origin.exc is None:
                            o = Outcome("raise", o.state,
                                        origin or o.origin)
                        out.append(o if o.kind != "fall"
                                   else Outcome("fall", o.state))
        elif not stmt.handlers:
            # try/finally only: exceptions pass through
            out.extend(Outcome("raise", s, origin)
                       for s, origin in entry_states)

        # orelse runs after a no-raise body
        for o in falls:
            if stmt.orelse:
                out.extend(self._walk_seq(list(stmt.orelse), o.state,
                                          trap))
            else:
                out.append(o)
        out.extend(others)

        if stmt.finalbody:
            finalized: List[Outcome] = []
            for o in out:
                fouts = self._walk_seq(list(stmt.finalbody), o.state,
                                       trap)
                for fo in fouts:
                    if fo.kind == "fall":
                        finalized.append(Outcome(o.kind, fo.state,
                                                 o.origin))
                    else:
                        finalized.append(fo)
            out = finalized
        return out


# --------------------------------------------------------- missing-rollback

def _attr_assigns(stmts: Sequence[ast.stmt]) -> List[Tuple[str, ast.AST]]:
    """``(field, node)`` for every tracked-field attribute assignment in
    ``stmts`` (recursive, tuple targets flattened, ``self`` excluded —
    engine-global state rolls back via ``_abort_step``, which per-field
    matching cannot see)."""
    out: List[Tuple[str, ast.AST]] = []
    for s in stmts:
        if isinstance(s, FunctionNode + (ast.ClassDef,)):
            continue
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = s.targets if isinstance(s, ast.Assign) \
                else [s.target]
            flat: List[ast.expr] = []
            for t in targets:
                flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
            for el in flat:
                if isinstance(el, ast.Attribute) and \
                        el.attr in ROLLBACK_FIELDS:
                    base = node_path(el.value)
                    if base and base.split(".")[0] not in ("self", "cls"):
                        out.append((el.attr, el))
        for fname in ("body", "orelse", "finalbody"):
            v = getattr(s, fname, None)
            if isinstance(v, list):
                out.extend(_attr_assigns(
                    [x for x in v if isinstance(x, ast.stmt)]))
        for h in getattr(s, "handlers", []) or []:
            out.extend(_attr_assigns(h.body))
    return out


def missing_rollback_findings(fi: FuncInfo, emap: EffectMap
                              ) -> List[RawFinding]:
    """Fire on the PR-6 shape gone wrong: a ``try`` whose handler
    re-raises mutates a request-lifecycle field without restoring it
    (any assignment to the same field in the handler or ``finally``)
    before the exception escapes."""
    out: List[RawFinding] = []
    for node in _own_stmts(fi.node):
        if not isinstance(node, ast.Try):
            continue
        rollback_handlers = [
            h for h in node.handlers
            if any(isinstance(x, ast.Raise) for x in _own_stmts_h(h))]
        if not rollback_handlers:
            continue
        if not any(emap.stmt_may_raise(s, fi)
                   for s in _shallow_stmts(node.body)):
            continue
        mutated = _attr_assigns(node.body)
        if not mutated:
            continue
        restored: Set[str] = {f for f, _ in
                              _attr_assigns(node.finalbody)}
        for h in rollback_handlers:
            restored_h = restored | {f for f, _ in _attr_assigns(h.body)}
            for fld, site in mutated:
                if fld not in restored_h:
                    out.append(RawFinding(
                        "missing-rollback", site,
                        f"request field `.{fld}` is mutated under a "
                        f"try whose handler re-raises without "
                        f"restoring it — snapshot it before the try "
                        f"and restore it in the except path "
                        f"(PR-6 rollback rule)", fi))
    return out


def _own_stmts_h(h: ast.ExceptHandler) -> Iterator[ast.stmt]:
    def rec(stmts):
        for s in stmts:
            yield s
            if isinstance(s, FunctionNode + (ast.ClassDef,)):
                continue
            for fname in ("body", "orelse", "finalbody"):
                v = getattr(s, fname, None)
                if isinstance(v, list):
                    yield from rec(v)
            for hh in getattr(s, "handlers", []) or []:
                yield from rec(hh.body)
    yield from rec(h.body)


def _shallow_stmts(stmts: Sequence[ast.stmt]) -> Iterator[ast.stmt]:
    for s in stmts:
        yield s
        if isinstance(s, FunctionNode + (ast.ClassDef,)):
            continue
        for fname in ("body", "orelse"):
            v = getattr(s, fname, None)
            if isinstance(v, list):
                yield from _shallow_stmts(
                    [x for x in v if isinstance(x, ast.stmt)])


# --------------------------------------------------------------- module API

def _has_effect_calls(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and classify_call(n) is not None:
            return True
    return False


def analyze_functions(index: ModuleIndex) -> List[RawFinding]:
    """All graftown raw findings for one module: the shared entry point
    the five rules split by id (computed once, cached per file)."""
    emap = EffectMap(index)
    out: List[RawFinding] = []
    for fi in index.functions.values():
        if not isinstance(fi.node, FunctionNode):
            continue
        out.extend(missing_rollback_findings(fi, emap))
        if not _has_effect_calls(fi.node):
            continue
        w = _Walker(fi, emap)
        w.walk_function()
        out.extend(w.findings)
    return out
