"""AST indexing and lightweight per-function dataflow for graftlint.

Everything here is plain :mod:`ast` over a single module — no imports
of jax, no execution.  The two exported pieces are:

* :class:`ModuleIndex` — finds every jit-wrapped callable in a module
  (``jax.jit(f)``, ``@jax.jit``, ``@partial(jax.jit, ...)``, the
  watchdog's ``_WatchedJit(f)`` re-wrap seam), resolves the wrapped
  target back to its ``def``/``lambda``, records which attribute the
  wrapper is bound to (``self._admit_jit = jax.jit(...)``) together
  with its ``donate_argnums``/``static_argnums``, and transitively
  marks helpers called from traced code as traced themselves.
* small dataflow helpers (:func:`flatten_statements`,
  :func:`node_path`, :func:`reads_tainted`, :func:`stmt_exprs`) used by
  the rules for linear, source-order taint tracking inside one
  function.

The analysis is deliberately intraprocedural and order-linear: branch
joins are approximated by source order.  That trades soundness for a
near-zero false-positive rate on this codebase's idioms, which is what
lets the CI gate demand *zero* unsuppressed errors.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute reads that yield static (trace-time) metadata, not values
SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding",
               "itemsize", "nbytes"}


def node_path(node: ast.AST) -> Optional[str]:
    """Dotted path for a ``Name``/``Attribute`` chain (``self.pool.cache``),
    or ``None`` for anything more exotic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def flatten_statements(fn: ast.AST) -> List[ast.stmt]:
    """All statements of ``fn`` in source order, flattening compound
    bodies but *not* descending into nested function/class defs (those
    are analysed on their own)."""
    out: List[ast.stmt] = []

    def rec(stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            out.append(s)
            if isinstance(s, FunctionNode + (ast.ClassDef,)):
                continue
            for fname in ("body", "orelse", "finalbody"):
                v = getattr(s, fname, None)
                if isinstance(v, list):
                    rec([x for x in v if isinstance(x, ast.stmt)])
            for h in getattr(s, "handlers", []) or []:
                rec(h.body)

    body = getattr(fn, "body", [])
    if isinstance(body, ast.expr):   # lambda: body is a single expression
        wrapper = ast.Expr(value=body)
        ast.copy_location(wrapper, body)
        return [wrapper]
    rec(body)
    return out


def stmt_exprs(stmt: ast.stmt) -> Iterator[ast.expr]:
    """The expressions directly owned by ``stmt`` (not those of nested
    statements — the flattened walk visits them on their own)."""
    for fname, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v
                elif isinstance(v, ast.withitem):
                    yield v.context_expr


def walk_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    for e in stmt_exprs(stmt):
        yield from ast.walk(e)


def reads_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    """True when ``expr`` reads the *value* of a tainted path.

    Access through shape-like attributes (``x.shape``, ``x.dtype``) and
    ``len(x)`` is static under tracing and does not count as a value
    read — this is what keeps ``if x.shape[0]:`` and bucket arithmetic
    out of the recompile-hazard rule.
    """
    if not tainted:
        return False
    hit = False

    def rec(n: ast.AST) -> None:
        nonlocal hit
        if hit:
            return
        if isinstance(n, ast.Attribute) and n.attr in SHAPE_ATTRS:
            return
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return
        if isinstance(n, (ast.Name, ast.Attribute)):
            p = node_path(n)
            if p is not None and p in tainted:
                hit = True
                return
        for c in ast.iter_child_nodes(n):
            rec(c)

    rec(expr)
    return hit


def target_paths(target: ast.expr) -> List[str]:
    """Paths written by an assignment target (tuple targets flattened).
    Subscript targets report the path of the subscripted container —
    ``cs["index"] = ...`` writes into ``cs``."""
    out: List[str] = []
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            out.extend(target_paths(el))
        return out
    if isinstance(target, ast.Starred):
        return target_paths(target.value)
    if isinstance(target, ast.Subscript):
        p = node_path(target.value)
        return [p] if p else []
    p = node_path(target)
    return [p] if p else []


def _const_tuple(node: Optional[ast.expr]) -> Tuple[int, ...]:
    """Evaluate a literal int / tuple-of-int AST node, else ()."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: List[int] = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int) \
                    and not isinstance(el.value, bool):
                vals.append(el.value)
            else:
                return ()
        return tuple(vals)
    return ()


@dataclass
class FuncInfo:
    node: ast.AST                      # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    class_name: Optional[str] = None   # nearest enclosing class, if any
    parent: Optional["FuncInfo"] = None
    is_traced: bool = False
    jit_entry: bool = False            # directly wrapped (vs transitively)
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()

    def param_names(self) -> List[str]:
        a = getattr(self.node, "args", None)
        if a is None:
            return []
        names = [p.arg for p in
                 list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def traced_param_names(self) -> Set[str]:
        """Parameters that carry tracers when this function runs under
        jit: everything except ``self``/``cls`` and, for direct jit
        entries, the ``static_argnums`` positions (numbered over the
        *call* signature, i.e. after dropping ``self``)."""
        a = getattr(self.node, "args", None)
        if a is None:
            return set()
        pos = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        if self.class_name and pos and pos[0] in ("self", "cls"):
            pos = pos[1:]
        static = set(self.static_argnums) if self.jit_entry else set()
        out = {n for i, n in enumerate(pos) if i not in static}
        out.update(p.arg for p in a.kwonlyargs)
        return out


@dataclass
class JitBinding:
    """``<owner>.<attr> = jax.jit(target, ...)`` (or a module-level
    ``NAME = jax.jit(...)``) — the unit of the jit inventory."""
    attr: str
    class_name: Optional[str]          # class whose instances carry the attr
    lineno: int
    target_qualname: Optional[str]
    donate_argnums: Tuple[int, ...]
    static_argnums: Tuple[int, ...]
    via: str = "jax.jit"               # or "_WatchedJit"


class ModuleIndex:
    """Jit topology of one module: traced functions, wrapper bindings."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.functions: Dict[ast.AST, FuncInfo] = {}
        self.bindings: List[JitBinding] = []
        #: (class_name, attr) -> donate_argnums, for the donation rule
        self.donating_attrs: Dict[Tuple[Optional[str], str],
                                  Tuple[int, ...]] = {}
        self._jit_aliases: Set[str] = {"jax.jit"}
        self._partial_aliases: Set[str] = {"functools.partial"}
        self._collect_imports()
        self._collect_functions()
        self._collect_wraps()
        self._propagate_traced()

    # ------------------------------------------------------------ build
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for al in node.names:
                    name = al.asname or al.name
                    if mod == "jax" and al.name == "jit":
                        self._jit_aliases.add(name)
                    if mod == "functools" and al.name == "partial":
                        self._partial_aliases.add(name)
            elif isinstance(node, ast.Import):
                for al in node.names:
                    if al.name == "jax" and al.asname:
                        self._jit_aliases.add(f"{al.asname}.jit")

    def _collect_functions(self) -> None:
        index = self.functions

        def visit(node: ast.AST, qual: str, cls: Optional[str],
                  parent: Optional[FuncInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, f"{qual}{child.name}.", child.name, parent)
                elif isinstance(child, FunctionNode):
                    fi = FuncInfo(child, f"{qual}{child.name}", cls, parent)
                    index[child] = fi
                    visit(child, f"{qual}{child.name}.", cls, fi)
                elif isinstance(child, ast.Lambda):
                    fi = FuncInfo(child, f"{qual}<lambda>", cls, parent)
                    index[child] = fi
                    visit(child, f"{qual}<lambda>.", cls, fi)
                else:
                    visit(child, qual, cls, parent)

        visit(self.tree, "", None, None)

    def _is_jit_ref(self, node: ast.expr) -> bool:
        p = node_path(node)
        return p is not None and p in self._jit_aliases

    def _is_partial_ref(self, node: ast.expr) -> bool:
        p = node_path(node)
        return p is not None and (p in self._partial_aliases
                                  or p == "partial")

    def _jit_call_info(self, call: ast.Call):
        """If ``call`` is ``jax.jit(target, ...)`` or
        ``_WatchedJit(target, ...)``, return (target_expr, donate,
        static, via); else None."""
        via = None
        if self._is_jit_ref(call.func):
            via = "jax.jit"
        elif node_path(call.func) in ("_WatchedJit", "watchdog._WatchedJit"):
            via = "_WatchedJit"
        if via is None or not call.args:
            return None
        donate = static = ()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donate = _const_tuple(kw.value)
            elif kw.arg == "static_argnums":
                static = _const_tuple(kw.value)
        return call.args[0], donate, static, via

    def _resolve_target(self, expr: ast.expr,
                        scope: Optional[FuncInfo],
                        cls: Optional[str]) -> Optional[FuncInfo]:
        """Resolve the wrapped callable back to a function we indexed."""
        if isinstance(expr, ast.Lambda):
            return self.functions.get(expr)
        if isinstance(expr, ast.Name):
            # nearest enclosing function's nested defs, then module level
            s = scope
            while s is not None:
                for fi in self.functions.values():
                    if fi.parent is s and isinstance(fi.node, FunctionNode) \
                            and fi.node.name == expr.id:
                        return fi
                s = s.parent
            for fi in self.functions.values():
                if fi.parent is None and isinstance(fi.node, FunctionNode) \
                        and fi.node.name == expr.id:
                    return fi
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and cls is not None:
            for fi in self.functions.values():
                if fi.class_name == cls and isinstance(fi.node, FunctionNode) \
                        and fi.node.name == expr.attr:
                    return fi
        return None

    def _collect_wraps(self) -> None:
        # decorators: @jax.jit and @partial(jax.jit, ...)
        for node, fi in self.functions.items():
            for dec in getattr(node, "decorator_list", []):
                donate = static = ()
                hit = False
                if self._is_jit_ref(dec):
                    hit = True
                elif isinstance(dec, ast.Call) and self._is_jit_ref(dec.func):
                    hit = True
                    for kw in dec.keywords:
                        if kw.arg == "donate_argnums":
                            donate = _const_tuple(kw.value)
                        elif kw.arg == "static_argnums":
                            static = _const_tuple(kw.value)
                elif isinstance(dec, ast.Call) \
                        and self._is_partial_ref(dec.func) \
                        and dec.args and self._is_jit_ref(dec.args[0]):
                    hit = True
                    for kw in dec.keywords:
                        if kw.arg == "donate_argnums":
                            donate = _const_tuple(kw.value)
                        elif kw.arg == "static_argnums":
                            static = _const_tuple(kw.value)
                if hit:
                    fi.is_traced = fi.jit_entry = True
                    fi.donate_argnums = donate
                    fi.static_argnums = static

        # call-form wraps, possibly bound to an attribute
        class WrapVisitor(ast.NodeVisitor):
            def __init__(v, outer):
                v.outer = outer
                v.scope: List[FuncInfo] = []
                v.cls: List[str] = []

            def visit_ClassDef(v, node):
                v.cls.append(node.name)
                v.generic_visit(node)
                v.cls.pop()

            def _visit_fn(v, node):
                v.scope.append(v.outer.functions[node])
                v.generic_visit(node)
                v.scope.pop()

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_Lambda(v, node):
                v._visit_fn(node)

            def visit_Assign(v, node):
                v._handle_assign(node.targets, node.value)
                v.generic_visit(node)

            def visit_AnnAssign(v, node):
                if node.value is not None:
                    v._handle_assign([node.target], node.value)
                v.generic_visit(node)

            def _handle_assign(v, targets, value):
                # unwrap `jax.jit(...) if cond else None`-style guards
                if isinstance(value, ast.IfExp):
                    for arm in (value.body, value.orelse):
                        if isinstance(arm, ast.Call) and \
                                v.outer._jit_call_info(arm) is not None:
                            value = arm
                            break
                if not isinstance(value, ast.Call):
                    return
                info = v.outer._jit_call_info(value)
                if info is None:
                    return
                target_expr, donate, static, via = info
                cls = v.cls[-1] if v.cls else None
                scope = v.scope[-1] if v.scope else None
                fi = v.outer._resolve_target(target_expr, scope, cls)
                for t in targets:
                    attr = None
                    owner = None
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in ("self", "cls"):
                        attr, owner = t.attr, cls
                    elif isinstance(t, ast.Name):
                        attr, owner = t.id, None
                    if attr is None:
                        continue
                    v.outer.bindings.append(JitBinding(
                        attr=attr, class_name=owner, lineno=value.lineno,
                        target_qualname=fi.qualname if fi else None,
                        donate_argnums=donate, static_argnums=static,
                        via=via))
                    if donate:
                        v.outer.donating_attrs[(owner, attr)] = donate

            def visit_Call(v, node):
                info = v.outer._jit_call_info(node)
                if info is not None:
                    target_expr, donate, static, _via = info
                    cls = v.cls[-1] if v.cls else None
                    scope = v.scope[-1] if v.scope else None
                    fi = v.outer._resolve_target(target_expr, scope, cls)
                    if fi is not None:
                        fi.is_traced = fi.jit_entry = True
                        fi.donate_argnums = donate
                        fi.static_argnums = static
                v.generic_visit(node)

        WrapVisitor(self).visit(self.tree)

    def _propagate_traced(self) -> None:
        """Helpers called from traced code run under the same trace:
        follow bare-``Name`` calls, ``self.method()`` calls, and local
        aliases (``scatter = self._scatter_cols``) transitively."""
        by_name_module = {fi.node.name: fi for fi in self.functions.values()
                          if fi.parent is None
                          and isinstance(fi.node, FunctionNode)}
        methods: Dict[Tuple[str, str], FuncInfo] = {}
        for fi in self.functions.values():
            if fi.class_name and isinstance(fi.node, FunctionNode):
                methods[(fi.class_name, fi.node.name)] = fi

        def callees(fi: FuncInfo) -> List[FuncInfo]:
            out: List[FuncInfo] = []
            aliases: Dict[str, FuncInfo] = {}
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign) and \
                        isinstance(n.value, (ast.Name, ast.Attribute)):
                    cal = self._resolve_callee(n.value, fi, aliases,
                                               by_name_module, methods)
                    if cal is not None:
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                aliases[t.id] = cal
                if isinstance(n, ast.Call):
                    cal = self._resolve_callee(n.func, fi, aliases,
                                               by_name_module, methods)
                    if cal is not None:
                        out.append(cal)
            return out

        frontier = [fi for fi in self.functions.values() if fi.is_traced]
        while frontier:
            fi = frontier.pop()
            for cal in callees(fi):
                if not cal.is_traced:
                    cal.is_traced = True
                    frontier.append(cal)

    def _resolve_callee(self, func_expr, fi, aliases, by_name_module,
                        methods) -> Optional[FuncInfo]:
        if isinstance(func_expr, ast.Name):
            if func_expr.id in aliases:
                return aliases[func_expr.id]
            s = fi
            while s is not None:
                for cand in self.functions.values():
                    if cand.parent is s and \
                            isinstance(cand.node, FunctionNode) and \
                            cand.node.name == func_expr.id:
                        return cand
                s = s.parent
            return by_name_module.get(func_expr.id)
        if isinstance(func_expr, ast.Attribute) and \
                isinstance(func_expr.value, ast.Name) and \
                func_expr.value.id in ("self", "cls") and fi.class_name:
            return methods.get((fi.class_name, func_expr.attr))
        return None

    # ---------------------------------------------------------- queries
    def traced_functions(self) -> List[FuncInfo]:
        return [fi for fi in self.functions.values() if fi.is_traced]

    def host_functions(self) -> List[FuncInfo]:
        return [fi for fi in self.functions.values()
                if not fi.is_traced and isinstance(fi.node, FunctionNode)]

    def methods_of(self, class_name: str) -> Dict[str, FuncInfo]:
        return {fi.node.name: fi for fi in self.functions.values()
                if fi.class_name == class_name
                and isinstance(fi.node, FunctionNode)}

    def classes_with_method(self, method: str) -> List[str]:
        out = []
        for fi in self.functions.values():
            if fi.class_name and isinstance(fi.node, FunctionNode) \
                    and fi.node.name == method and fi.class_name not in out:
                out.append(fi.class_name)
        return out
