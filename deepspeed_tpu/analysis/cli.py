"""Command-line front end for graftlint (see ``bin/graftlint``).

Exit codes mirror ``check_regression.py``: 0 = gate passes, 1 =
unsuppressed errors above ``--max-errors``, 2 = unusable invocation
(bad path, bad baseline file) — a typo can never pass silently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .baseline import write_baseline
from .concurrency_rules import SYNC_RULES
from .ownership_rules import OWN_RULES
from .rules import ALL_RULES, META_RULES
from .runner import analyze_paths, check_paths, effect_inventory, \
    jit_inventory, thread_inventory
from .sharding_rules import SHARDING_RULES

#: the CI gate: these trees hold at zero unsuppressed errors
DEFAULT_GATE_PATHS = ("deepspeed_tpu/serving", "deepspeed_tpu/telemetry",
                      "deepspeed_tpu/parallel",
                      "deepspeed_tpu/runtime/engine.py")

#: interpreter finding ids (not Rule objects — emitted by enumeration)
INTERP_RULE_IDS = ("signature-escape", "unbounded-signature")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _default_paths() -> List[str]:
    # resolve the gate paths relative to the repo root (parent of the
    # package) so `bin/graftlint` works from any cwd
    cands = [os.path.join(_repo_root(), p) for p in DEFAULT_GATE_PATHS]
    return [c for c in cands if os.path.exists(c)]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="Static trace-safety analyzer for the serving stack "
                    "(stdlib ast only — no jax import, runs in "
                    "milliseconds).")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the CI "
                         "gate — deepspeed_tpu/serving + telemetry)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout "
                         "(schema: {version, summary, findings})")
    ap.add_argument("--baseline", metavar="FILE",
                    help="fingerprint file of grandfathered findings")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current unsuppressed findings as the new "
                         "baseline and exit 0")
    ap.add_argument("--select", action="append", default=[],
                    metavar="RULE", help="run only these rule ids "
                    "(repeatable)")
    ap.add_argument("--ignore", action="append", default=[],
                    metavar="RULE", help="skip these rule ids (repeatable)")
    ap.add_argument("--max-errors", type=int, default=0, metavar="N",
                    help="tolerated unsuppressed+unbaselined errors "
                         "(default 0)")
    ap.add_argument("--tier", choices=("all", "lint", "sync", "own"),
                    default="all",
                    help="rule tier: 'lint' = trace-safety rules only, "
                         "'sync' = graftsync thread-context/async-safety "
                         "rules only, 'own' = graftown ownership/"
                         "exception-path rules only, 'all' (default) = "
                         "every tier")
    ap.add_argument("--threads", action="store_true",
                    help="print the inferred thread-context map "
                         "(qualname -> LOOP|ENGINE|BOTH|EXECUTOR) as "
                         "JSON and exit (graftsync drift check)")
    ap.add_argument("--effects", action="store_true",
                    help="print the graftown effect table plus every "
                         "inferred per-function resource-effect summary "
                         "as JSON and exit (ownership drift check)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--inventory", action="store_true",
                    help="print the static jit-wrapper inventory as JSON "
                         "and exit (watchdog coverage drift check)")
    ap.add_argument("--check", action="store_true",
                    help="the graftcheck tier: lint + sharding rules plus "
                         "the abstract interpreter's signature "
                         "enumeration (finiteness proof); with "
                         "--manifest, also diff static vs runtime "
                         "warmup signatures")
    ap.add_argument("--manifest", metavar="FILE",
                    help="signatures.json warmup manifest exported by "
                         "`bench.py --signatures` — re-enumerates under "
                         "the manifest's recorded configs and fails on "
                         "any static/runtime divergence (implies "
                         "--check)")
    ap.add_argument("--signatures", nargs="?", const="-", metavar="FILE",
                    help="with --inventory: also emit the statically "
                         "enumerated program -> sorted abstract "
                         "signature list as JSON (to FILE, or stdout "
                         "when bare) — a manifest reproducible without "
                         "jax")
    ap.add_argument("--verbose", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    check_tier = args.check or args.manifest is not None

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:22s} [{r.severity}] {r.short}")
        for r in SYNC_RULES:
            print(f"{r.id:26s} [{r.severity}] {r.short}  (sync tier)")
        for r in OWN_RULES:
            print(f"{r.id:26s} [{r.severity}] {r.short}  (own tier)")
        for r in SHARDING_RULES:
            print(f"{r.id:22s} [{r.severity}] {r.short}  (--check)")
        for rid in INTERP_RULE_IDS:
            print(f"{rid:22s} [error] abstract signature enumeration  "
                  f"(--check)")
        for rid, desc in META_RULES.items():
            print(f"{rid:22s} [meta]  {desc}")
        return 0

    known = {r.id for r in ALL_RULES} | {r.id for r in SYNC_RULES} \
        | {r.id for r in OWN_RULES}
    if check_tier:
        known |= {r.id for r in SHARDING_RULES} | set(INTERP_RULE_IDS)
        if args.tier != "all":
            print("graftlint: --tier cannot narrow --check (use --select)",
                  file=sys.stderr)
            return 2
    for rid in list(args.select) + list(args.ignore):
        if rid not in known:
            print(f"graftlint: unknown rule id '{rid}' "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    if not paths:
        print("graftlint: no paths given and default gate dirs not found",
              file=sys.stderr)
        return 2

    if args.threads:
        try:
            tmap = thread_inventory(paths)
        except FileNotFoundError as e:
            print(f"graftlint: no such path: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"version": 1, "files": tmap},
                         indent=2, sort_keys=True))
        return 0

    if args.effects:
        try:
            emap = effect_inventory(paths)
        except FileNotFoundError as e:
            print(f"graftlint: no such path: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"version": 1, **emap},
                         indent=2, sort_keys=True))
        return 0

    if args.inventory:
        try:
            inv = jit_inventory(paths)
        except FileNotFoundError as e:
            print(f"graftlint: no such path: {e}", file=sys.stderr)
            return 2
        if args.signatures:
            from .interp import default_check_envs, enumerate_union
            envs = default_check_envs()
            res = enumerate_union(envs, _repo_root())
            doc = {"version": 1, "configs": envs,
                   "programs": {k: sorted(v)
                                for k, v in sorted(res.programs.items())}}
            if args.signatures == "-":
                print(json.dumps(doc, indent=2, sort_keys=True))
            else:
                with open(args.signatures, "w", encoding="utf-8") as fh:
                    json.dump(doc, fh, indent=2, sort_keys=True)
                    fh.write("\n")
                print(f"graftlint: wrote {sum(map(len, res.programs.values()))}"
                      f" signature(s) across {len(res.programs)} program(s)"
                      f" to {args.signatures}")
        else:
            print(json.dumps(inv, indent=2))
        return 0

    manifest = None
    if args.manifest is not None:
        try:
            with open(args.manifest, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"graftlint: cannot read manifest: {e}", file=sys.stderr)
            return 2
        if not isinstance(manifest.get("programs"), dict):
            print(f"graftlint: {args.manifest} is not a signatures.json "
                  "manifest (missing 'programs')", file=sys.stderr)
            return 2

    try:
        if check_tier:
            envs = manifest.get("configs") if manifest else None
            report = check_paths(paths, root=_repo_root(),
                                 envs=envs or None,
                                 select=args.select or None,
                                 ignore=args.ignore or None,
                                 baseline=args.baseline)
        else:
            tier_rules = None            # "all": lint + sync + own
            if args.tier == "lint":
                tier_rules = ALL_RULES
            elif args.tier == "sync":
                tier_rules = SYNC_RULES
            elif args.tier == "own":
                tier_rules = OWN_RULES
            report = analyze_paths(paths, select=args.select or None,
                                   ignore=args.ignore or None,
                                   baseline=args.baseline,
                                   rules=tier_rules)
    except FileNotFoundError as e:
        print(f"graftlint: no such path: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    manifest_diffs: List[str] = []
    if manifest is not None:
        from .interp import default_check_envs, diff_manifest, \
            enumerate_union
        envs = manifest.get("configs") or default_check_envs()
        res = enumerate_union(envs, _repo_root())
        static = {k: sorted(v) for k, v in res.programs.items()}
        manifest_diffs = diff_manifest(static, manifest["programs"])

    if args.write_baseline:
        n = write_baseline(args.write_baseline, report.findings)
        print(f"graftlint: wrote {n} finding(s) to {args.write_baseline}")
        return 0

    if args.json:
        doc = json.loads(report.to_json())
        if manifest is not None:
            doc["manifest"] = {"path": args.manifest,
                               "diffs": manifest_diffs}
        print(json.dumps(doc, indent=2))
    else:
        print(report.format_human(verbose=args.verbose))
        if manifest is not None:
            if manifest_diffs:
                print(f"manifest divergence vs {args.manifest}:")
                for d in manifest_diffs:
                    print(f"  {d}")
            else:
                print(f"manifest: static signature set matches "
                      f"{args.manifest} exactly")

    if manifest_diffs:
        return 1
    return 1 if report.errors > args.max_errors else 0
