"""Command-line front end for graftlint (see ``bin/graftlint``).

Exit codes mirror ``check_regression.py``: 0 = gate passes, 1 =
unsuppressed errors above ``--max-errors``, 2 = unusable invocation
(bad path, bad baseline file) — a typo can never pass silently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .baseline import write_baseline
from .rules import ALL_RULES, META_RULES
from .runner import analyze_paths, jit_inventory

#: the CI gate: these trees hold at zero unsuppressed errors
DEFAULT_GATE_PATHS = ("deepspeed_tpu/serving", "deepspeed_tpu/telemetry")


def _default_paths() -> List[str]:
    # resolve the gate dirs relative to the repo root (parent of the
    # package) so `bin/graftlint` works from any cwd
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cands = [os.path.join(here, p) for p in DEFAULT_GATE_PATHS]
    return [c for c in cands if os.path.isdir(c)]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="Static trace-safety analyzer for the serving stack "
                    "(stdlib ast only — no jax import, runs in "
                    "milliseconds).")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the CI "
                         "gate — deepspeed_tpu/serving + telemetry)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout "
                         "(schema: {version, summary, findings})")
    ap.add_argument("--baseline", metavar="FILE",
                    help="fingerprint file of grandfathered findings")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current unsuppressed findings as the new "
                         "baseline and exit 0")
    ap.add_argument("--select", action="append", default=[],
                    metavar="RULE", help="run only these rule ids "
                    "(repeatable)")
    ap.add_argument("--ignore", action="append", default=[],
                    metavar="RULE", help="skip these rule ids (repeatable)")
    ap.add_argument("--max-errors", type=int, default=0, metavar="N",
                    help="tolerated unsuppressed+unbaselined errors "
                         "(default 0)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--inventory", action="store_true",
                    help="print the static jit-wrapper inventory as JSON "
                         "and exit (watchdog coverage drift check)")
    ap.add_argument("--verbose", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id:22s} [{r.severity}] {r.short}")
        for rid, desc in META_RULES.items():
            print(f"{rid:22s} [meta]  {desc}")
        return 0

    known = {r.id for r in ALL_RULES}
    for rid in list(args.select) + list(args.ignore):
        if rid not in known:
            print(f"graftlint: unknown rule id '{rid}' "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    if not paths:
        print("graftlint: no paths given and default gate dirs not found",
              file=sys.stderr)
        return 2

    if args.inventory:
        try:
            inv = jit_inventory(paths)
        except FileNotFoundError as e:
            print(f"graftlint: no such path: {e}", file=sys.stderr)
            return 2
        print(json.dumps(inv, indent=2))
        return 0

    try:
        report = analyze_paths(paths, select=args.select or None,
                               ignore=args.ignore or None,
                               baseline=args.baseline)
    except FileNotFoundError as e:
        print(f"graftlint: no such path: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        n = write_baseline(args.write_baseline, report.findings)
        print(f"graftlint: wrote {n} finding(s) to {args.write_baseline}")
        return 0

    if args.json:
        print(report.to_json())
    else:
        print(report.format_human(verbose=args.verbose))

    return 1 if report.errors > args.max_errors else 0
