"""File discovery, rule execution, pragma/baseline application.

:func:`analyze_paths` is the programmatic entry point (the CLI and the
CI gate test both call it); :func:`analyze_source` runs the rules over
an in-memory snippet (the fixture tests).  Neither imports jax — a full
run over ``deepspeed_tpu/serving + telemetry`` is pure-stdlib and takes
well under a second.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from .baseline import apply_baseline, load_baseline
from .concurrency import ThreadContextMap
from .concurrency_rules import SYNC_RULES
from .dataflow import ModuleIndex
from .findings import ERROR, WARNING, Finding, assign_fingerprints
from .ownership import EffectMap, effect_table_dict
from .ownership_rules import OWN_RULES
from .pragmas import PragmaIndex
from .rules import ALL_RULES, ModuleContext, Rule

SCHEMA_VERSION = 1

#: the default ("all tiers") rule set: trace-safety lints + the
#: graftsync thread-context rules + the graftown ownership rules.
#: Sharding rules and the abstract interpreter join via
#: ``check_paths`` (they need project context).
DEFAULT_RULES = tuple(ALL_RULES) + tuple(SYNC_RULES) + tuple(OWN_RULES)


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    files: int = 0

    # ------------------------------------------------------------ counts
    @property
    def errors(self) -> int:
        return sum(1 for f in self.findings if f.counts_as_error)

    @property
    def warnings(self) -> int:
        return sum(1 for f in self.findings
                   if f.severity == WARNING and not f.suppressed
                   and not f.baselined)

    @property
    def suppressed(self) -> int:
        return sum(1 for f in self.findings if f.suppressed)

    @property
    def baselined(self) -> int:
        return sum(1 for f in self.findings if f.baselined)

    # ------------------------------------------------------------ output
    def to_dict(self) -> Dict[str, object]:
        return {
            "version": SCHEMA_VERSION,
            "summary": {
                "files": self.files,
                "total": len(self.findings),
                "errors": self.errors,
                "warnings": self.warnings,
                "suppressed": self.suppressed,
                "baselined": self.baselined,
            },
            "findings": [f.to_dict()
                         for f in sorted(self.findings,
                                         key=lambda x: x.sort_key())],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def format_human(self, verbose: bool = False) -> str:
        lines = []
        for f in sorted(self.findings, key=lambda x: x.sort_key()):
            if (f.suppressed or f.baselined) and not verbose:
                continue
            lines.append(f.format_human())
        lines.append(
            f"graftlint: {len(self.findings)} finding(s) in {self.files} "
            f"file(s) — {self.errors} error(s), {self.warnings} "
            f"warning(s), {self.suppressed} suppressed, "
            f"{self.baselined} baselined")
        return "\n".join(lines)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(root, fn))
        else:
            raise FileNotFoundError(p)
    return out


def _relpath(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def analyze_source(source: str, path: str = "<memory>",
                   rules: Optional[Sequence[Rule]] = None,
                   extra_findings: Optional[Sequence[Finding]] = None
                   ) -> List[Finding]:
    """Run rules + pragma handling over one in-memory module.

    ``extra_findings`` are pre-computed findings for this file (the
    abstract interpreter's project-level signature findings); they join
    the rule findings *before* pragma application so ``allow[...]``
    comments and fingerprints treat them like any rule output.
    """
    rules = list(rules) if rules is not None else list(DEFAULT_RULES)
    findings: List[Finding] = list(extra_findings or [])
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(Finding(
            rule="parse-error", severity=ERROR, path=path,
            line=e.lineno or 1, col=(e.offset or 0) + 1,
            message=f"file does not parse: {e.msg}"))
        assign_fingerprints(findings, source.splitlines())
        return findings

    index = ModuleIndex(tree)
    ctx = ModuleContext(path, source, tree, index)
    for rule in rules:
        findings.extend(rule.check(ctx))

    pragmas = PragmaIndex.from_source(source)
    for f in findings:
        p = pragmas.lookup(f.line, f.rule)
        if p is not None:
            p.used = True
            if p.reason:
                f.suppressed = True
                f.suppress_reason = p.reason
            # a reasonless pragma does NOT suppress: the finding stays
            # an error and the pragma itself is flagged below
    # a pragma naming only rules that did not run this pass (e.g. an
    # allow[signature-escape] seen by a lint-only run) is not stale —
    # it belongs to another tier
    active_ids = {r.id for r in rules}
    if extra_findings:
        active_ids.update(f.rule for f in extra_findings)
    for p in pragmas.all_pragmas():
        if not p.reason:
            findings.append(Finding(
                rule="pragma-missing-reason", severity=ERROR, path=path,
                line=p.line, col=1,
                message="graftlint pragma without `-- reason`: every "
                        "suppression must say why the invariant does "
                        "not apply here"))
        elif not p.used:
            if "*" not in p.rules and not p.rules & active_ids:
                continue  # pragma is for a tier that did not run
            findings.append(Finding(
                rule="unused-pragma", severity=WARNING, path=path,
                line=p.line, col=1,
                message=f"pragma allow[{','.join(sorted(p.rules))}] "
                        "matched no finding — stale allowance, remove it"))
    assign_fingerprints(findings, source.splitlines())
    return findings


def analyze_paths(paths: Sequence[str],
                  select: Optional[Iterable[str]] = None,
                  ignore: Optional[Iterable[str]] = None,
                  baseline: Optional[str] = None,
                  rules: Optional[Sequence[Rule]] = None) -> Report:
    rules = list(rules) if rules is not None else list(DEFAULT_RULES)
    if select:
        chosen = set(select)
        rules = [r for r in rules if r.id in chosen]
    if ignore:
        dropped = set(ignore)
        rules = [r for r in rules if r.id not in dropped]

    report = Report()
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        report.files += 1
        report.findings.extend(
            analyze_source(source, _relpath(fp), rules))

    if baseline:
        apply_baseline(report.findings, load_baseline(baseline))
    return report


def check_paths(paths: Sequence[str],
                root: str = ".",
                envs: Optional[Sequence[dict]] = None,
                select: Optional[Iterable[str]] = None,
                ignore: Optional[Iterable[str]] = None,
                baseline: Optional[str] = None) -> Report:
    """The ``--check`` tier: lint rules + sharding rules over ``paths``
    plus the abstract interpreter's signature enumeration over the
    serving stack under ``root``.

    Interpreter findings (``signature-escape`` / ``unbounded-signature``)
    are merged into their source file's finding list before pragma
    application, so they suppress and fingerprint exactly like rule
    output.  Manifest comparison is separate (see ``cli.py``): a
    static/runtime divergence is a CI diff, not a source finding.
    """
    from .interp import default_check_envs, enumerate_union
    from .sharding_rules import SHARDING_RULES

    rules: List[Rule] = list(DEFAULT_RULES) + list(SHARDING_RULES)
    if select:
        chosen = set(select)
        rules = [r for r in rules if r.id in chosen]
    if ignore:
        dropped = set(ignore)
        rules = [r for r in rules if r.id not in dropped]

    res = enumerate_union(envs if envs is not None
                          else default_check_envs(), root)
    by_file: Dict[str, List[Finding]] = {}
    for f in res.findings:
        by_file.setdefault(f.path, []).append(f)

    report = Report()
    seen_files = set()
    for fp in iter_python_files(paths):
        rel = _relpath(fp)
        seen_files.add(rel)
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        report.files += 1
        report.findings.extend(analyze_source(
            source, rel, rules, extra_findings=by_file.get(rel, [])))
    # interpreter findings in files outside `paths` still count — the
    # enumeration is a whole-project property
    for rel, extra in by_file.items():
        if rel in seen_files:
            continue
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            report.findings.extend(extra)
            continue
        report.files += 1
        report.findings.extend(analyze_source(source, rel, [],
                                              extra_findings=extra))

    if baseline:
        apply_baseline(report.findings, load_baseline(baseline))
    return report


def thread_inventory(paths: Sequence[str]) -> Dict[str, Dict[str, str]]:
    """The inferred thread-context map (graftsync's ``--threads`` dump):
    ``relpath -> {qualname: LOOP|ENGINE|BOTH|EXECUTOR}`` for every
    function with a context, deterministic across runs — the input to
    the thread-context drift test."""
    out: Dict[str, Dict[str, str]] = {}
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=fp)
        except SyntaxError:
            continue
        labels = ThreadContextMap(ModuleIndex(tree)).labels()
        if labels:
            out[_relpath(fp)] = labels
    return out


def effect_inventory(paths: Sequence[str]) -> Dict[str, object]:
    """The graftown ``--effects`` dump: the declarative effect table
    plus every inferred per-function resource-effect summary under
    ``paths`` — deterministic across runs, the input to the effect
    drift test (both directions: a primitive dropped from the table
    and a new lifecycle helper both show up as a diff)."""
    files: Dict[str, Dict[str, object]] = {}
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=fp)
        except SyntaxError:
            continue
        labels = EffectMap(ModuleIndex(tree)).labels()
        if labels:
            files[_relpath(fp)] = labels
    return {"table": effect_table_dict(), "files": files}


def jit_inventory(paths: Sequence[str]) -> List[Dict[str, object]]:
    """Statically enumerate every jit-wrapper binding (``self.attr =
    jax.jit(...)`` / module-level ``NAME = jax.jit(...)``) under
    ``paths`` — the input to the watchdog-coverage drift test."""
    out: List[Dict[str, object]] = []
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=fp)
        except SyntaxError:
            continue
        index = ModuleIndex(tree)
        for b in index.bindings:
            out.append({
                "path": _relpath(fp),
                "line": b.lineno,
                "class": b.class_name,
                "attr": b.attr,
                "target": b.target_qualname,
                "donate_argnums": list(b.donate_argnums),
                "static_argnums": list(b.static_argnums),
                "via": b.via,
            })
    return out
