"""graftlint rules — each one distills a past incident into a check.

=====================  ========================================================
rule id                origin
=====================  ========================================================
recompile-hazard       PR 5: the recompile watchdog exists because shape- or
                       value-dependent Python inside a jitted function retraces
                       per value; this rule catches ``.item()`` / ``int(x)`` /
                       ``if x:`` / ``range(len(x))`` on traced values before a
                       trace ever runs.
uncommitted-buffer     PR 5: an uncommitted ``jnp.zeros`` KV cache held as
                       ``self.*`` state double-compiled every program the first
                       post-placement step (committed vs uncommitted layouts).
donation-after-use     the ``donate_argnums=(0,)`` admit/decode paths: a read
                       of a buffer after it was donated to a jit call observes
                       freed memory.
unsafe-scatter         PR 7: dynamic-index ``.at[...].set`` defaults to *clamp*
                       on OOB, silently aliasing row 0 / row N-1; every dynamic
                       scatter must pick its ``mode=`` explicitly.
hot-loop-host-sync     PR 8's cost model exists because stray host syncs
                       (``np.asarray`` / ``.item()`` / ``block_until_ready``)
                       in ``ServingEngine.step``-reachable code serialise the
                       device pipeline; each one must be a deliberate,
                       pragma-documented choice.
=====================  ========================================================

Rules yield :class:`~.findings.Finding` objects; the runner applies
pragmas and the baseline afterwards.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .dataflow import (FunctionNode, ModuleIndex, flatten_statements,
                       node_path, reads_tainted, target_paths, walk_exprs)
from .findings import ERROR, Finding


class ModuleContext:
    def __init__(self, path: str, source: str, tree: ast.Module,
                 index: ModuleIndex):
        self.path = path
        self.source = source
        self.tree = tree
        self.index = index


class Rule:
    id: str = ""
    severity: str = ERROR
    short: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                func: str = "") -> Finding:
        return Finding(rule=self.id, severity=self.severity, path=ctx.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message, func=func)


# --------------------------------------------------------------------------
class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    severity = ERROR
    short = ("Python-value-dependent control flow or host conversion "
             "inside a jitted function")

    _CASTS = {"int", "float", "bool"}
    _NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fi in ctx.index.traced_functions():
            tainted: Set[str] = set(fi.traced_param_names())
            if not tainted:
                continue
            for stmt in flatten_statements(fi.node):
                yield from self._scan_stmt(ctx, fi, stmt, tainted)
                self._propagate(stmt, tainted)

    def _propagate(self, stmt: ast.stmt, tainted: Set[str]) -> None:
        if isinstance(stmt, ast.Assign):
            val, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            val, targets = stmt.value, [stmt.target]
        elif isinstance(stmt, ast.AugAssign):
            val, targets = stmt.value, [stmt.target]
        else:
            return
        is_tainted = reads_tainted(val, tainted)
        for t in targets:
            for p in target_paths(t):
                if is_tainted:
                    tainted.add(p)
                elif not isinstance(stmt, ast.AugAssign):
                    tainted.discard(p)

    def _scan_stmt(self, ctx, fi, stmt, tainted) -> Iterator[Finding]:
        if isinstance(stmt, (ast.If, ast.While)):
            t = stmt.test
            if self._is_bare_truth(t, tainted):
                kind = "while" if isinstance(stmt, ast.While) else "if"
                yield self.finding(
                    ctx, t,
                    f"`{kind}` on a traced value retraces per boolean "
                    "(use jnp.where / lax.cond)", fi.qualname)
        if isinstance(stmt, ast.For) and isinstance(stmt.iter, ast.Call):
            it = stmt.iter
            if isinstance(it.func, ast.Name) and it.func.id == "range" \
                    and it.args and isinstance(it.args[0], ast.Call):
                inner = it.args[0]
                if isinstance(inner.func, ast.Name) \
                        and inner.func.id == "len" and inner.args \
                        and self._names_tainted(inner.args[0], tainted):
                    yield self.finding(
                        ctx, it,
                        "`range(len(...))` over a traced value unrolls "
                        "and retraces per length (use lax.fori_loop or a "
                        "static bucket)", fi.qualname)
        for n in walk_exprs(stmt):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "item" \
                    and not n.args and reads_tainted(f.value, tainted):
                yield self.finding(
                    ctx, n, "`.item()` on a traced value forces a "
                    "concrete value at trace time", fi.qualname)
            elif isinstance(f, ast.Name) and f.id in self._CASTS \
                    and n.args and reads_tainted(n.args[0], tainted):
                yield self.finding(
                    ctx, n, f"`{f.id}()` on a traced value forces a "
                    "concrete value at trace time", fi.qualname)
            else:
                p = node_path(f)
                if p in self._NP_SINKS and n.args \
                        and reads_tainted(n.args[0], tainted):
                    yield self.finding(
                        ctx, n, f"`{p}()` on a traced value materialises "
                        "it at trace time", fi.qualname)

    @staticmethod
    def _names_tainted(expr: ast.expr, tainted: Set[str]) -> bool:
        p = node_path(expr)
        return p is not None and p in tainted

    def _is_bare_truth(self, test: ast.expr, tainted: Set[str]) -> bool:
        """Only bare truthiness of a traced value: ``if x:``,
        ``if not x:``, boolean combinations of those.  Comparisons and
        membership tests are deliberately excluded (``if key not in
        cs:`` over a dict of arrays is static)."""
        if isinstance(test, ast.BoolOp):
            return any(self._is_bare_truth(v, tainted) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._is_bare_truth(test.operand, tainted)
        p = node_path(test)
        return p is not None and p in tainted


# --------------------------------------------------------------------------
class UncommittedBufferRule(Rule):
    id = "uncommitted-buffer"
    severity = ERROR
    short = ("jnp allocation stored as long-lived self.* state without a "
             "device_put/sharding commit")

    _SOURCES = {"zeros", "ones", "full", "empty",
                "zeros_like", "ones_like", "full_like", "empty_like"}

    def _is_source_call(self, n: ast.AST) -> bool:
        if not isinstance(n, ast.Call):
            return False
        p = node_path(n.func)
        if p is None or "." not in p:
            return False
        root, _, fn = p.rpartition(".")
        return fn in self._SOURCES and root in ("jnp", "jax.numpy")

    def _is_commit_call(self, n: ast.AST) -> bool:
        return isinstance(n, ast.Call) and \
            node_path(n.func) in ("jax.device_put", "device_put")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fi in ctx.index.host_functions():
            uncommitted: Set[str] = set()
            for stmt in flatten_statements(fi.node):
                # commit: any device_put over an uncommitted var cleanses
                # it (the committed result replaces or shadows the raw
                # allocation; conditional commits count — we only chase
                # the obviously-never-committed case)
                for n in walk_exprs(stmt):
                    if self._is_commit_call(n):
                        for arg in n.args[:1]:
                            for p in self._paths_in(arg):
                                uncommitted.discard(p)
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    targets = stmt.targets if isinstance(stmt, ast.Assign) \
                        else [stmt.target]
                    value = stmt.value
                    if value is None:
                        continue
                    val_uncommitted = self._value_uncommitted(
                        value, uncommitted)
                    for t in targets:
                        yield from self._apply_target(
                            ctx, fi, t, value, val_uncommitted, uncommitted)

    def _paths_in(self, expr: ast.AST) -> List[str]:
        out = []
        for n in ast.walk(expr):
            p = node_path(n) if isinstance(n, (ast.Name, ast.Attribute)) \
                else None
            if p:
                out.append(p)
        return out

    def _value_uncommitted(self, value: ast.expr,
                           uncommitted: Set[str]) -> bool:
        if self._is_commit_call(value):
            return False
        for n in ast.walk(value):
            if self._is_commit_call(n):
                # a commit somewhere inside (e.g. dict of device_put
                # results) — treat the whole value as committed unless a
                # raw source also appears outside it; keep it simple and
                # call it committed
                return False
        if any(self._is_source_call(n) for n in ast.walk(value)):
            return True
        return reads_tainted(value, uncommitted)

    def _apply_target(self, ctx, fi, target, value, val_uncommitted,
                      uncommitted) -> Iterator[Finding]:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                yield from self._apply_target(ctx, fi, el, value,
                                              val_uncommitted, uncommitted)
            return
        p = node_path(target) or (
            node_path(target.value) if isinstance(target, ast.Subscript)
            else None)
        if p is None:
            return
        if p.startswith("self.") or p.startswith("cls."):
            if val_uncommitted:
                yield self.finding(
                    ctx, target,
                    f"`{p}` holds a jnp allocation that was never "
                    "committed with jax.device_put — long-lived state "
                    "compiles against an uncommitted layout and "
                    "recompiles once placed (PR 5 bug class)",
                    fi.qualname)
            return
        if val_uncommitted:
            uncommitted.add(p)
        elif not isinstance(target, ast.Subscript):
            uncommitted.discard(p)


# --------------------------------------------------------------------------
#: wrapper-attribute name -> donated *call-site* argument positions, for
#: call sites whose wrapper is defined in another module (the engine
#: calling pool/engine jits).  Module-local ``jax.jit(...,
#: donate_argnums=...)`` bindings are discovered from the AST and take
#: precedence.
DONATION_FALLBACK: Dict[str, Tuple[int, ...]] = {
    "_jit_decode": (1,),
    "_jit_prefill_chunk": (1,),
    "_jit_decode_scan": (1,),
    "_jit_copy_page": (0,),
    "_jit_scatter_pages": (0,),
    "_admit_jit": (0,),
    "_admit_rows_jit": (0,),
    "_paged_decode_jit": (1,),
    "_paged_verify_jit": (1,),
    "_paged_chunk_jit": (1,),
    "verify_k": (0,),
    "prefill_chunk": (0,),
}


class DonationAfterUseRule(Rule):
    id = "donation-after-use"
    severity = ERROR
    short = "read of a buffer after it was donated to a jit call"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        donating = dict(ctx.index.donating_attrs)
        for fi in ctx.index.functions.values():
            if not isinstance(fi.node, FunctionNode) or fi.is_traced:
                continue
            stmts = flatten_statements(fi.node)
            # donated path -> (donation node, wrapper name)
            live: Dict[str, Tuple[ast.AST, str]] = {}
            for stmt in stmts:
                # reads of already-donated paths (donations from
                # *earlier* statements only)
                if live:
                    yield from self._scan_reads(ctx, fi, stmt, live)
                for n in walk_exprs(stmt):
                    if isinstance(n, ast.Call):
                        for path, wrapper in self._donations(
                                n, fi, donating):
                            live[path] = (n, wrapper)
                # kills: assignment to the donated path (or a prefix of
                # it) re-binds the name to the fresh result
                for t in self._stmt_targets(stmt):
                    for tp in target_paths(t):
                        for path in list(live):
                            if path == tp or path.startswith(tp + "."):
                                del live[path]

    def _stmt_targets(self, stmt: ast.stmt) -> List[ast.expr]:
        if isinstance(stmt, ast.Assign):
            return stmt.targets
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            return [stmt.target]
        return []

    def _donations(self, call: ast.Call, fi, donating):
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name is None:
            return
        argnums: Optional[Tuple[int, ...]] = None
        if isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Name) and \
                call.func.value.id in ("self", "cls"):
            argnums = donating.get((fi.class_name, name))
        if argnums is None:
            argnums = DONATION_FALLBACK.get(name)
        if not argnums:
            return
        for i in argnums:
            if i < len(call.args):
                p = node_path(call.args[i])
                if p is None and isinstance(call.args[i], ast.Subscript):
                    p = node_path(call.args[i].value)
                if p is not None:
                    yield p, name

    def _scan_reads(self, ctx, fi, stmt, live) -> Iterator[Finding]:
        for n in walk_exprs(stmt):
            if isinstance(n, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(n, "ctx", None), ast.Load):
                p = node_path(n)
                if p is None:
                    continue
                for path, (don, wrapper) in live.items():
                    if p == path or p.startswith(path + "."):
                        yield self.finding(
                            ctx, n,
                            f"`{p}` is read after being donated to "
                            f"`{wrapper}` (donate_argnums) at line "
                            f"{don.lineno} — the donated buffer is "
                            "freed by XLA and must be rebound from the "
                            "call's result first", fi.qualname)
                        break


# --------------------------------------------------------------------------
class UnsafeScatterRule(Rule):
    id = "unsafe-scatter"
    severity = ERROR
    short = "dynamic-index .at[].set/add without an explicit mode="

    _METHODS = {"set", "add", "subtract", "multiply", "mul", "divide",
                "div", "power", "min", "max", "apply"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        funcs = {id(fi.node): fi for fi in ctx.index.functions.values()}
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in self._METHODS
                    and isinstance(f.value, ast.Subscript)
                    and isinstance(f.value.value, ast.Attribute)
                    and f.value.value.attr == "at"):
                continue
            if any(kw.arg == "mode" for kw in n.keywords):
                continue
            idx = f.value.slice
            if self._is_static(idx):
                continue
            qual = self._enclosing(ctx, n)
            yield self.finding(
                ctx, n,
                f"dynamic-index `.at[...].{f.attr}` without an explicit "
                "`mode=` — the default clamps out-of-bounds indices onto "
                "live rows (PR 7 aliasing class); state intent with "
                'mode="drop" (or "promise_in_bounds")', qual)

    def _is_static(self, idx: ast.expr) -> bool:
        if isinstance(idx, ast.Tuple):
            return all(self._is_static(el) for el in idx.elts)
        if isinstance(idx, ast.Slice):
            return all(x is None or self._is_static(x)
                       for x in (idx.lower, idx.upper, idx.step))
        if isinstance(idx, ast.Constant):
            return True
        if isinstance(idx, ast.UnaryOp) and \
                isinstance(idx.op, (ast.USub, ast.UAdd)):
            return self._is_static(idx.operand)
        return False

    def _enclosing(self, ctx: ModuleContext, node: ast.AST) -> str:
        best = ""
        best_span = None
        for fi in ctx.index.functions.values():
            lo = getattr(fi.node, "lineno", None)
            hi = getattr(fi.node, "end_lineno", None)
            if lo is None or hi is None:
                continue
            if lo <= node.lineno <= hi:
                span = hi - lo
                if best_span is None or span < best_span:
                    best, best_span = fi.qualname, span
        return best


# --------------------------------------------------------------------------
class HotLoopHostSyncRule(Rule):
    id = "hot-loop-host-sync"
    severity = ERROR
    short = ("host sync on a device value inside ServingEngine.step-"
             "reachable code")

    #: engine/pool entry points that return device arrays
    _DEVICE_FNS = {"run_decode", "run_verify", "run_prefill_chunk",
                   "verify_k", "prefill_chunk", "prefill_last"}
    _NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    _CASTS = {"int", "float", "bool"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for cls in ctx.index.classes_with_method("step"):
            methods = ctx.index.methods_of(cls)
            reachable = self._reachable(methods, "step")
            for name in sorted(reachable):
                fi = methods[name]
                if fi.is_traced:
                    continue
                yield from self._scan_method(ctx, fi)

    def _reachable(self, methods, root) -> Set[str]:
        seen = {root} if root in methods else set()
        frontier = list(seen)
        while frontier:
            cur = methods[frontier.pop()]
            for n in ast.walk(cur.node):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self" and \
                        n.func.attr in methods and \
                        n.func.attr not in seen:
                    seen.add(n.func.attr)
                    frontier.append(n.func.attr)
        return seen

    def _is_device_source(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr.startswith("_jit") or f.attr in self._DEVICE_FNS:
                return True
        p = node_path(f)
        if p is None:
            return False
        return p.startswith("jnp.") or p.startswith("jax.numpy.") \
            or p.startswith("jax.random.")

    def _expr_device(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """Does ``expr`` evaluate to a device value?  Calls are opaque
        barriers unless they are known device sources — a helper like
        ``self._sample(logits)`` syncs internally and hands back a host
        array, and charging its *caller* too would double-count every
        sync."""
        if isinstance(expr, ast.Call):
            return self._is_device_source(expr)
        if isinstance(expr, ast.Attribute):
            if expr.attr in {"shape", "ndim", "dtype", "size"}:
                return False
            p = node_path(expr)
            if p is not None and p in tainted:
                return True
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        return any(self._expr_device(c, tainted)
                   for c in ast.iter_child_nodes(expr))

    def _sink(self, call: ast.Call, tainted: Set[str]):
        """Return a message when ``call`` host-syncs a device value."""
        f = call.func
        p = node_path(f)
        if p in self._NP_SINKS and call.args \
                and self._expr_device(call.args[0], tainted):
            return f"`{p}` copies a device value to host"
        if p == "jax.block_until_ready" and call.args \
                and self._expr_device(call.args[0], tainted):
            return "`jax.block_until_ready` stalls on a device value"
        if isinstance(f, ast.Name) and f.id in self._CASTS and call.args \
                and self._expr_device(call.args[0], tainted):
            return f"`{f.id}()` blocks on a device value"
        if isinstance(f, ast.Attribute) and \
                f.attr in ("item", "tolist", "block_until_ready") and \
                self._expr_device(f.value, tainted):
            return f"`.{f.attr}()` blocks on a device value"
        if isinstance(f, ast.Attribute) and f.attr == "stop":
            # Timer.stop(block_on=...) exists to block_until_ready the
            # values it is handed — it IS a host sync, whatever the
            # taint tracker knows about the bundle's provenance
            for kw in call.keywords:
                if kw.arg == "block_on" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None):
                    return ("`stop(block_on=...)` blocks until the "
                            "device values it is handed exist")
        return None

    def _scan_method(self, ctx, fi) -> Iterator[Finding]:
        tainted: Set[str] = set()
        for stmt in flatten_statements(fi.node):
            emitted_lines = set()
            for n in walk_exprs(stmt):
                if isinstance(n, ast.Call):
                    msg = self._sink(n, tainted)
                    if msg and n.lineno not in emitted_lines:
                        emitted_lines.add(n.lineno)
                        yield self.finding(
                            ctx, n,
                            f"{msg} inside step-reachable "
                            "`{}` — every post-warmup host sync "
                            "serialises the decode pipeline; if "
                            "deliberate, allow it with a pragma and a "
                            "reason".format(fi.qualname), fi.qualname)
            self._propagate(stmt, tainted)

    def _propagate(self, stmt: ast.stmt, tainted: Set[str]) -> None:
        if isinstance(stmt, ast.Assign):
            val, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            val, targets = stmt.value, [stmt.target]
        else:
            return
        is_sink_result = isinstance(val, ast.Call) and \
            self._sink(val, tainted) is not None
        # a sink call's *result* lives on host: the assignment both
        # emits the finding (above) and cleanses the target
        device = (not is_sink_result) and self._expr_device(val, tainted)
        for t in targets:
            for p in target_paths(t):
                if device:
                    tainted.add(p)
                else:
                    tainted.discard(p)


ALL_RULES: List[Rule] = [
    RecompileHazardRule(),
    UncommittedBufferRule(),
    DonationAfterUseRule(),
    UnsafeScatterRule(),
    HotLoopHostSyncRule(),
]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in ALL_RULES}

#: meta-diagnostics emitted by the runner, documented alongside rules
META_RULES: Dict[str, str] = {
    "pragma-missing-reason": "a graftlint pragma must carry `-- reason`",
    "unused-pragma": "a graftlint pragma matched no finding",
    "parse-error": "file does not parse",
}
