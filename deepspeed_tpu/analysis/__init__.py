"""graftlint — static trace-safety analysis for the serving stack.

An AST-based analyzer (stdlib :mod:`ast` only, no jax import) that
turns the repo's hardest-won runtime invariants into CI-time rules:

* ``recompile-hazard`` — value-dependent Python inside jitted code
* ``uncommitted-buffer`` — ``jnp.zeros``-class allocations stored as
  long-lived ``self.*`` state without a ``jax.device_put`` commit
* ``donation-after-use`` — reads of a buffer after it was passed to a
  ``donate_argnums`` call site
* ``unsafe-scatter`` — dynamic-index ``.at[...].set/add`` without an
  explicit ``mode=``
* ``hot-loop-host-sync`` — host syncs on device values in
  ``ServingEngine.step``-reachable code

The **graftsync** tier (``--tier sync``, on by default) adds
thread-context inference over the PR-11 asyncio front end — every
function is classified LOOP / ENGINE / BOTH (``--threads`` dumps the
map) — and five async-safety rules on top of it:
``blocking-call-in-coroutine``, ``cross-thread-engine-access``,
``unsafe-future-resolution``, ``await-while-holding-lock``, and
``unguarded-shared-write`` (catalog: :mod:`.concurrency_rules`).

The **graftown** tier (``--tier own``, on by default) infers a
resource-effect summary per function from a declarative effect table
of the serving primitives (slot/page/seat/future/lock — ``--effects``
dumps the inferred map) and walks each function's control flow
including exception edges to prove the lifecycle invariants that
``check_invariants()`` audits at runtime: ``leak-on-exception-path``,
``double-release``, ``use-after-release``, ``unbalanced-refcount``,
and ``missing-rollback`` (catalog: :mod:`.ownership_rules`).

See ``bin/graftlint`` for the CLI and the "Static analysis" section of
the README for the rule catalog, pragma syntax and baseline workflow.
Findings are suppressed per line with::

    # graftlint: allow[rule-id] -- reason

This package must stay importable without jax so the CI gate runs in
milliseconds (``bin/graftlint`` loads it standalone, bypassing the
heavyweight ``deepspeed_tpu`` package import).
"""

from .baseline import load_baseline, write_baseline  # noqa: F401
from .concurrency import ThreadContextMap  # noqa: F401
from .concurrency_rules import SYNC_RULE_IDS, SYNC_RULES  # noqa: F401
from .findings import ERROR, INFO, WARNING, Finding  # noqa: F401
from .interp import (default_check_envs, diff_manifest,  # noqa: F401
                     enumerate_signatures, enumerate_union)
from .ownership import (EFFECT_TABLE, RUNTIME_AUDIT,  # noqa: F401
                        EffectMap, effect_table_dict)
from .ownership_rules import OWN_RULE_IDS, OWN_RULES  # noqa: F401
from .pragmas import PragmaIndex  # noqa: F401
from .rules import ALL_RULES, META_RULES, RULES_BY_ID  # noqa: F401
from .runner import (DEFAULT_RULES, Report, analyze_paths,  # noqa: F401
                     analyze_source, check_paths, effect_inventory,
                     iter_python_files, jit_inventory, thread_inventory)
from .sharding_rules import CHECK_RULE_IDS, SHARDING_RULES  # noqa: F401

__all__ = [
    "ALL_RULES", "CHECK_RULE_IDS", "DEFAULT_RULES", "EFFECT_TABLE",
    "META_RULES", "OWN_RULES", "OWN_RULE_IDS", "RULES_BY_ID",
    "RUNTIME_AUDIT", "SYNC_RULES", "SYNC_RULE_IDS", "ERROR",
    "WARNING", "INFO", "EffectMap", "Finding", "PragmaIndex", "Report",
    "ThreadContextMap", "analyze_paths",
    "analyze_source", "check_paths", "default_check_envs", "diff_manifest",
    "effect_inventory", "effect_table_dict", "enumerate_signatures",
    "enumerate_union", "iter_python_files",
    "jit_inventory", "load_baseline", "thread_inventory", "write_baseline",
]
