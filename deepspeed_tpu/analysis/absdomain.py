"""Abstract value domain for graftcheck (see ``interp.py``).

The domain models exactly what a jit cache key sees at the watched
call seams: *top-level* argument structure.  Arrays are abstracted to
``dtype[dim, ...]``; pytree containers (params, caches) to ``*`` — the
serving invariants live in the small dense operands (bucketed widths,
batch buckets, positions), not inside the parameter tree; Python
scalars reaching a jit boundary in this codebase are always
``static_argnums`` operands, so they render by *value*.

Dims are members of a small integer lattice:

* :class:`Known` — a concrete int (``8``),
* :class:`IntRange` — an int in ``[lo, hi]`` (a prompt length),
* :class:`FiniteSet` — one of an explicit finite set (``{2, 4, 8}``,
  the power-of-two bucket sets the admission code produces),
* :class:`Unbounded` — no finite bound could be established.

A :class:`FiniteSet` keeps its python identity through the
interpreter, so one abstract batch size flowing into several operand
shapes of the same call expands *jointly* (``ids (nB, W)`` and
``slots (nB,)`` always agree) while independent sets expand as a
cartesian product.  :func:`expand_signatures` is the only place that
expansion happens.

Runtime twin: :func:`~deepspeed_tpu.telemetry.watchdog.manifest_signature`
renders live call args with the same grammar; the two must stay
byte-identical for the manifest diff to mean anything (pinned by
tests/unit/analysis/test_signatures.py round-trip fixtures).
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable, List, Optional, Sequence, Tuple

#: expanding one call site beyond this many concrete signatures is
#: reported as unbounded rather than enumerated — a legitimate serving
#: program has log2-bounded bucket sets, not hundreds of variants
MAX_SIGNATURES_PER_SITE = 512

# placements for the placement-mix rule (PR-5/PR-8 incident class):
# HOST values (numpy) adopt the committed layout of the pool they meet;
# UNCOMMITTED jnp allocations carry their own default layout and force
# a second executable when mixed with committed state.
HOST = "host"
COMMITTED = "committed"
UNCOMMITTED = "uncommitted"


class Dim:
    """Base class for abstract integer dimensions."""

    def values(self) -> Optional[Tuple[int, ...]]:
        """Concrete candidates, or None when unbounded."""
        raise NotImplementedError


class Known(Dim):
    __slots__ = ("v",)

    def __init__(self, v: int):
        self.v = int(v)

    def values(self):
        return (self.v,)

    def __repr__(self):
        return f"Known({self.v})"


class IntRange(Dim):
    """An integer somewhere in ``[lo, hi]`` (inclusive)."""

    __slots__ = ("lo", "hi", "name")

    def __init__(self, lo: int, hi: int, name: str = ""):
        self.lo, self.hi, self.name = int(lo), int(hi), name

    def clamp(self, lo: Optional[int] = None,
              hi: Optional[int] = None) -> "IntRange":
        nlo = self.lo if lo is None else max(self.lo, lo)
        nhi = self.hi if hi is None else min(self.hi, hi)
        return IntRange(nlo, nhi, self.name)

    def values(self):
        # a raw range is only enumerable when small; bucket functions
        # are expected to collapse ranges into FiniteSets first
        if self.hi - self.lo + 1 <= MAX_SIGNATURES_PER_SITE:
            return tuple(range(self.lo, self.hi + 1))
        return None

    def __repr__(self):
        n = f" {self.name}" if self.name else ""
        return f"IntRange({self.lo}..{self.hi}{n})"


class FiniteSet(Dim):
    """One of an explicit, small set of ints.  Identity matters: the
    same object appearing in several shapes expands jointly."""

    __slots__ = ("vals", "name")

    def __init__(self, vals: Iterable[int], name: str = ""):
        self.vals = tuple(sorted({int(v) for v in vals}))
        self.name = name
        if not self.vals:
            raise ValueError("FiniteSet needs at least one value")

    def values(self):
        return self.vals

    def __repr__(self):
        n = f" {self.name}" if self.name else ""
        return f"FiniteSet({list(self.vals)}{n})"


class Unbounded(Dim):
    __slots__ = ("why",)

    def __init__(self, why: str = ""):
        self.why = why

    def values(self):
        return None

    def __repr__(self):
        return f"Unbounded({self.why})"


def pow2_buckets(lo: int, hi: int, name: str = "") -> FiniteSet:
    """The power-of-two set ``{lo, 2*lo, ..} ∩ [lo, >=hi]`` produced by
    the admission code's doubling loops (``b = MIN; while b < n: b *= 2``)."""
    vals = []
    b = int(lo)
    while True:
        vals.append(b)
        if b >= hi:
            break
        b *= 2
    return FiniteSet(vals, name)


def dim_of(x: Any) -> Dim:
    if isinstance(x, Dim):
        return x
    if isinstance(x, bool):
        raise TypeError("bool is not a dim")
    if isinstance(x, int):
        return Known(x)
    raise TypeError(f"not a dim: {x!r}")


# ----------------------------------------------------------------------
# abstract values
# ----------------------------------------------------------------------
class AbsValue:
    """Base class for abstract runtime values."""


class Arr(AbsValue):
    """An array-like (numpy or jax) with abstract shape/dtype and a
    placement tag for the placement-mix rule."""

    __slots__ = ("shape", "dtype", "placement")

    def __init__(self, shape: Sequence[Any], dtype: str,
                 placement: str = HOST):
        self.shape: Tuple[Dim, ...] = tuple(dim_of(d) for d in shape)
        self.dtype = str(dtype)
        self.placement = placement

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def with_dtype(self, dtype: str) -> "Arr":
        return Arr(self.shape, dtype, self.placement)

    def with_placement(self, placement: str) -> "Arr":
        return Arr(self.shape, self.dtype, placement)

    def __repr__(self):
        return f"Arr({self.dtype}[{', '.join(map(repr, self.shape))}])"


class Tree(AbsValue):
    """An opaque pytree container (params / cache / prefill cache):
    renders as ``*``.  Carries a placement for the placement-mix rule."""

    __slots__ = ("placement", "label")

    def __init__(self, placement: str = COMMITTED, label: str = ""):
        self.placement = placement
        self.label = label

    def __repr__(self):
        return f"Tree({self.label or '*'})"


class Scalar(AbsValue):
    """A python scalar reaching a call boundary.  ``value`` may be a
    concrete python value (rendered by repr) or a :class:`Dim` for an
    abstract int."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def as_dim(self) -> Dim:
        if isinstance(self.value, Dim):
            return self.value
        if isinstance(self.value, bool):
            raise TypeError("bool scalar is not a dim")
        if isinstance(self.value, int):
            return Known(self.value)
        raise TypeError(f"not an int scalar: {self.value!r}")

    def __repr__(self):
        return f"Scalar({self.value!r})"


class Tup(AbsValue):
    """A python tuple/list of abstract values (NOT an operand pytree —
    use :class:`Tree` for those).  Exists so multi-value returns can be
    unpacked."""

    __slots__ = ("items",)

    def __init__(self, items: Sequence[AbsValue]):
        self.items = tuple(items)

    def __repr__(self):
        return f"Tup({list(self.items)!r})"


class Obj(AbsValue):
    """A host object with modelled attributes (a Request, a pool)."""

    __slots__ = ("kind", "attrs")

    def __init__(self, kind: str, attrs: Optional[dict] = None):
        self.kind = kind
        self.attrs = dict(attrs or {})

    def __repr__(self):
        return f"Obj({self.kind})"


class Unknown(AbsValue):
    """Analysis gave up on this value; reaching a watched call operand
    with one of these is the ``signature-escape`` finding."""

    __slots__ = ("why",)

    def __init__(self, why: str = ""):
        self.why = why

    def __repr__(self):
        return f"Unknown({self.why})"


# ----------------------------------------------------------------------
# signature rendering
# ----------------------------------------------------------------------
class SignatureError(ValueError):
    """A call's operands cannot be rendered into a finite signature
    set.  ``kind`` is the rule id the caller should report."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


def _collect_dims(vals: Sequence[AbsValue]) -> List[Dim]:
    """Distinct non-Known dims across the operand shapes, by identity."""
    out: List[Dim] = []
    seen = set()
    for v in vals:
        dims: Tuple[Dim, ...] = ()
        if isinstance(v, Arr):
            dims = v.shape
        elif isinstance(v, Scalar) and isinstance(v.value, Dim):
            dims = (v.value,)
        for d in dims:
            if isinstance(d, Known):
                continue
            if id(d) not in seen:
                seen.add(id(d))
                out.append(d)
    return out


def _render_one(v: AbsValue, env: dict) -> str:
    if isinstance(v, Arr):
        parts = []
        for d in v.shape:
            if id(d) in env:
                parts.append(str(env[id(d)]))
            elif isinstance(d, Known):
                parts.append(str(d.v))
            else:  # pragma: no cover - guarded by _collect_dims
                raise SignatureError("signature-escape",
                                     f"unexpanded dim {d!r}")
        return f"{v.dtype}[{','.join(parts)}]"
    if isinstance(v, Tree):
        return "*"
    if isinstance(v, Scalar):
        val = v.value
        if isinstance(val, Dim):
            if id(val) in env:
                return repr(env[id(val)])
            if isinstance(val, Known):
                return repr(val.v)
            raise SignatureError("signature-escape",
                                 f"unexpanded scalar dim {val!r}")
        return repr(val)
    if isinstance(v, Unknown):
        raise SignatureError(
            "signature-escape",
            f"operand value escaped the abstract domain"
            f"{': ' + v.why if v.why else ''}")
    raise SignatureError("signature-escape",
                         f"unrenderable operand {type(v).__name__}")


def expand_signatures(args: Sequence[AbsValue],
                      kwargs: Optional[dict] = None) -> List[str]:
    """All concrete manifest signatures this abstract call expands to.

    Dims expand by object identity — one :class:`FiniteSet` appearing
    in several operand shapes takes the same value in every expansion.
    Raises :class:`SignatureError` (kind ``unbounded-signature``) when
    any dim has no finite candidate set or the cartesian product
    exceeds :data:`MAX_SIGNATURES_PER_SITE`, and (kind
    ``signature-escape``) when an operand is :class:`Unknown`.
    """
    kwargs = kwargs or {}
    ordered = list(args) + [kwargs[k] for k in sorted(kwargs)]
    for v in ordered:  # fail fast on escapes before expanding
        if isinstance(v, Unknown):
            _render_one(v, {})
    dims = _collect_dims(ordered)
    axes = []
    total = 1
    for d in dims:
        vals = d.values()
        if vals is None:
            raise SignatureError(
                "unbounded-signature",
                f"dim {d!r} has no finite bound")
        total *= len(vals)
        if total > MAX_SIGNATURES_PER_SITE:
            raise SignatureError(
                "unbounded-signature",
                f"signature set exceeds {MAX_SIGNATURES_PER_SITE} "
                f"concrete variants")
        axes.append(vals)
    out = []
    names = sorted(kwargs)
    for combo in itertools.product(*axes) if axes else [()]:
        env = {id(d): val for d, val in zip(dims, combo)}
        parts = [_render_one(a, env) for a in args]
        parts += [f"{k}={_render_one(kwargs[k], env)}" for k in names]
        out.append("(" + ", ".join(parts) + ")")
    return sorted(set(out))
